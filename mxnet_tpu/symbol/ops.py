"""Symbolic operator namespace (reference: mxnet.symbol ops).

Registers pure kernels (shared with ops/nn_ops.py) under stable names so
graphs serialise, and exposes the reference's symbol-level API
(sym.FullyConnected, sym.Activation, ...).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

import numpy as _np

from ..base import MXNetError, _np_dtype
from ..ops import nn_ops as K
from .symbol import (Symbol, _make, register_aux_slots, register_op,
                     register_shape_rule, register_train_op)

__all__ = ["FullyConnected", "Convolution", "StemConvS2D", "Activation",
           "BatchNorm", "Deconvolution", "InstanceNorm", "GroupNorm", "PReLU",
           "LayerNorm", "Pooling", "Dropout", "Embedding", "softmax",
           "log_softmax", "SoftmaxOutput", "LinearRegressionOutput",
           "MAERegressionOutput", "LogisticRegressionOutput",
           "flatten", "Flatten", "reshape", "Custom", "RNN",
           "slice", "slice_axis",
           "SequenceMask", "SequenceLast", "SequenceReverse",
           "smooth_l1", "softmin", "hard_sigmoid",
           "cast", "Cast", "take",
           "LRN", "L2Normalization", "UpSampling", "BlockGrad",
           "stop_gradient", "MakeLoss", "SliceChannel", "split",
           "transpose", "concat", "Concat", "dot", "batch_dot", "sum", "mean",
           "max", "min", "relu", "sigmoid", "tanh", "exp", "log", "sqrt",
           "square", "negative", "zeros", "ones", "broadcast_add",
           "broadcast_mul", "elemwise_add", "expand_dims", "squeeze",
           "where", "shape_array", "_dynamic_arange", "broadcast_lesser",
           "broadcast_lesser_equal", "broadcast_greater",
           "broadcast_greater_equal"]

# -- elemwise registry -------------------------------------------------------
register_op("elemwise_add", jnp.add)
register_op("elemwise_sub", jnp.subtract)
register_op("elemwise_mul", jnp.multiply)
register_op("elemwise_div", jnp.divide)
register_op("elemwise_pow", jnp.power)
register_op("elemwise_add_scalar", lambda a, scalar: a + scalar)
register_op("elemwise_sub_scalar", lambda a, scalar: a - scalar)
register_op("elemwise_mul_scalar", lambda a, scalar: a * scalar)
register_op("elemwise_div_scalar", lambda a, scalar: a / scalar)
register_op("elemwise_pow_scalar", lambda a, scalar: a ** scalar)
register_op("rsub_scalar", lambda a, scalar: scalar - a)
register_op("rdiv_scalar", lambda a, scalar: scalar / a)
# comparisons return float 0/1 arrays (reference: broadcast_lesser etc.)
register_op("broadcast_lesser",
            lambda a, b: (a < b).astype(jnp.float32))
register_op("broadcast_lesser_equal",
            lambda a, b: (a <= b).astype(jnp.float32))
register_op("broadcast_greater",
            lambda a, b: (a > b).astype(jnp.float32))
register_op("broadcast_greater_equal",
            lambda a, b: (a >= b).astype(jnp.float32))
register_op("broadcast_lesser_scalar",
            lambda a, scalar: (a < scalar).astype(jnp.float32))
register_op("broadcast_lesser_equal_scalar",
            lambda a, scalar: (a <= scalar).astype(jnp.float32))
register_op("broadcast_greater_scalar",
            lambda a, scalar: (a > scalar).astype(jnp.float32))
register_op("broadcast_greater_equal_scalar",
            lambda a, scalar: (a >= scalar).astype(jnp.float32))
register_op("negative", jnp.negative)
register_op("relu", jax.nn.relu)
register_op("sigmoid", jax.nn.sigmoid)
register_op("tanh", jnp.tanh)
register_op("exp", jnp.exp)
register_op("log", jnp.log)
register_op("sqrt", jnp.sqrt)
register_op("square", jnp.square)
def _softmax_kernel(a, *length, axis=-1, use_length=False, causal=False):
    """Softmax with optional masking of the softmax axis (reference:
    softmax(..., use_length=True), src/operator/nn/softmax.cc; the causal
    flag is the attention-export extension). `length` has shape (B,) =
    data's leading dim; positions >= length along the (last) softmax axis
    are excluded. causal=True additionally masks positions past the query
    row (axis -2). -1e9 (not -inf) keeps fully-masked rows finite and
    matches the ONNX export decomposition bit-for-bit."""
    if not length and not causal:
        return jax.nn.softmax(a, axis=axis)
    if axis % a.ndim != a.ndim - 1:
        raise MXNetError("softmax: masking supports the last axis only")
    keep = jnp.ones((), bool)
    idx = jnp.arange(a.shape[-1])
    if length:
        (ln,) = length
        lb = ln.astype(jnp.int32).reshape(
            (ln.shape[0],) + (1,) * (a.ndim - 1))
        keep = keep & (idx < lb)
    if causal:
        rows = jnp.arange(a.shape[-2])[:, None]
        keep = keep & (idx[None, :] <= rows)
    return jax.nn.softmax(jnp.where(keep, a, -1e9), axis=-1)


register_op("softmax", _softmax_kernel)
register_op("log_softmax", lambda a, axis=-1: jax.nn.log_softmax(a, axis=axis))
register_op("sum", lambda a, axis=None, keepdims=False:
            jnp.sum(a, axis=axis, keepdims=keepdims))
register_op("mean", lambda a, axis=None, keepdims=False:
            jnp.mean(a, axis=axis, keepdims=keepdims))
register_op("max", lambda a, axis=None, keepdims=False:
            jnp.max(a, axis=axis, keepdims=keepdims))
register_op("min", lambda a, axis=None, keepdims=False:
            jnp.min(a, axis=axis, keepdims=keepdims))
# reference reshape magic codes (0 = copy input dim) resolved against the
# concrete input shape at execution; -1 passes through to jnp
register_op("reshape", lambda a, shape: a.reshape(
    tuple(a.shape[i] if s == 0 else s for i, s in enumerate(shape))))
register_op("flatten", lambda a: a.reshape(a.shape[0], -1))
register_op("transpose", lambda a, axes=None: jnp.transpose(a, axes))
register_op("expand_dims", lambda a, axis: jnp.expand_dims(a, axis))
register_op("squeeze", lambda a, axis=None: jnp.squeeze(a, axis))
register_op("concat", lambda *xs, dim=1: jnp.concatenate(xs, axis=dim))
register_op("dot", jnp.dot)
register_op("batch_dot", jnp.matmul)
register_op("FullyConnected",
            lambda x, w, *b, no_bias=False, num_hidden=None, flatten=True:
            K.fully_connected(x, w, b[0] if b else None, flatten))
register_op("Convolution",
            lambda x, w, *b, kernel=None, stride=1, pad=0, dilate=1,
            num_filter=None, num_group=1, no_bias=False, layout=None:
            K.convolution(x, w, b[0] if b else None, stride, pad, dilate,
                          num_group, layout))
register_op("Deconvolution",
            lambda x, w, *b, kernel=None, stride=1, pad=0, adj=0,
            num_filter=None, no_bias=False, layout=None:
            K.deconvolution(x, w, b[0] if b else None, stride, pad, adj,
                            layout))
register_op("StemConvS2D",
            lambda x, w, num_filter=None: K.stem_conv_s2d(x, w))
register_op("Activation", lambda x, act_type="relu": K.activation(x, act_type))
def _bn_infer(x, g, b, mm, mv, eps=1e-5, momentum=0.9, axis=1,
              fix_gamma=False, use_global_stats=False):
    if fix_gamma:
        g = jnp.ones_like(g)
    return K.batch_norm(x, g, b, mm, mv, eps, momentum, False, axis)[0]


register_op("BatchNorm", _bn_infer)


def _bn_train_variant(x, g, b, mm, mv, eps=1e-5, momentum=0.9, axis=1,
                      fix_gamma=False, use_global_stats=False, _rng=None):
    """Training BatchNorm: batch stats normalise, moving stats update
    (reference: BN's mutable aux inputs written during the forward).
    use_global_stats freezes the moving stats (fine-tune mode)."""
    if fix_gamma:
        g = jnp.ones_like(g)
    if use_global_stats:
        return K.batch_norm(x, g, b, mm, mv, eps, momentum, False, axis)[0], {}
    y, new_mm, new_mv = K.batch_norm(x, g, b, mm, mv, eps, momentum, True,
                                     axis)
    return y, {3: new_mm, 4: new_mv}


register_train_op("BatchNorm", _bn_train_variant)
register_aux_slots("BatchNorm", {3: "zeros", 4: "ones"})  # mean, var
register_op("LayerNorm", lambda x, g, b, axis=-1, eps=1e-5:
            K.layer_norm(x, g, b, axis, eps))
register_op("InstanceNorm", lambda x, g, b, eps=1e-5:
            K.instance_norm(x, g, b, eps))
register_op("GroupNorm", lambda x, g, b, num_groups=1, eps=1e-5:
            K.group_norm(x, g, b, num_groups, eps))
register_op("PReLU", K.prelu)
register_op("Pooling",
            lambda x, kernel=None, pool_type="max", stride=None, pad=0,
            global_pool=False, layout=None, count_include_pad=True:
            K.global_pooling(x, pool_type, layout or "NCHW") if global_pool
            else K.pooling(x, kernel, pool_type, stride, pad, layout,
                           count_include_pad))
register_op("Dropout", lambda x, p=0.5: x)  # inference: identity


def _dropout_train(x, p=0.5, _rng=None):
    """Inverted dropout for Executor.forward(is_train=True); the key is a
    per-node fold of the step key the Executor draws each forward."""
    if not p or _rng is None:
        return x, {}
    keep = jax.random.bernoulli(_rng, 1 - p, x.shape)
    return jnp.where(keep, x / (1 - p), 0).astype(x.dtype), {}


register_train_op("Dropout", _dropout_train)
register_op("Embedding", lambda i, w, input_dim=None, output_dim=None:
            K.embedding(i, w))


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _softmax_output_op(x, label, use_ignore, ignore_label, normalization,
                       grad_scale):
    return jax.nn.softmax(x, axis=-1)


def _so_fwd(x, label, use_ignore, ignore_label, normalization, grad_scale):
    p = jax.nn.softmax(x, axis=-1)
    return p, (p, label)


def _so_bwd(use_ignore, ignore_label, normalization, grad_scale, res, g):
    """Loss-head backward (reference: src/operator/softmax_output-inl.h):
    the cotangent is ignored; grad = (p - onehot(label)) * grad_scale,
    with ignore_label rows zeroed when use_ignore (padding positions —
    essential for bucketed LM training), 'valid' dividing by the
    non-ignored label count and 'batch' by the leading dim."""
    p, label = res
    ilab = label.astype(jnp.int32)
    oh = jax.nn.one_hot(ilab, p.shape[-1], dtype=p.dtype)
    grad = (p - oh) * grad_scale
    if use_ignore:
        keep = (ilab != int(ignore_label)).astype(p.dtype)
        grad = grad * keep[..., None]
        valid_cnt = jnp.maximum(keep.sum(), 1.0)
    else:
        valid_cnt = float(int(_np.prod(label.shape)))
    if normalization == "valid":
        grad = grad / valid_cnt
    elif normalization == "batch":
        grad = grad / p.shape[0]
    return (grad, jnp.zeros(label.shape, label.dtype))


_softmax_output_op.defvjp(_so_fwd, _so_bwd)


def _softmax_output_eval(x, *l, use_ignore=False, ignore_label=-1,
                         normalization="null", grad_scale=1.0):
    if not l:
        return jax.nn.softmax(x, axis=-1)
    return _softmax_output_op(x, l[0], bool(use_ignore), int(ignore_label),
                              normalization, float(grad_scale))


register_op("SoftmaxOutput", _softmax_output_eval)


def _regression_output(link, grad_fn):
    """Loss-head factory (reference: src/operator/regression_output-inl.h):
    forward applies the link; backward ignores the incoming cotangent and
    emits grad_fn(pred, label) * grad_scale / num_output, where num_output
    is the per-sample element count — the reference's exact scaling."""

    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def op(x, label, grad_scale):
        return link(x)

    def fwd(x, label, grad_scale):
        p = link(x)
        return p, (p, label)

    def bwd(grad_scale, res, g):
        p, label = res
        lab = label.reshape(p.shape).astype(p.dtype)
        # NB: plain `max` here would resolve to the symbol-level reduce op
        # this module exports — use the product directly (empty shape -> 1)
        num_output = int(_np.prod(p.shape[1:])) or 1
        return (grad_fn(p, lab) * (grad_scale / num_output),
                jnp.zeros(label.shape, label.dtype))

    op.defvjp(fwd, bwd)
    return lambda x, *l, grad_scale=1.0: (
        op(x, l[0], float(grad_scale)) if l else link(x))


register_op("LinearRegressionOutput",
            _regression_output(lambda x: x, lambda p, y: p - y))
register_op("MAERegressionOutput",
            _regression_output(lambda x: x, lambda p, y: jnp.sign(p - y)))
register_op("LogisticRegressionOutput",
            _regression_output(jax.nn.sigmoid, lambda p, y: p - y))
register_op("zeros", lambda shape=(), dtype=None: jnp.zeros(shape, dtype))
register_op("ones", lambda shape=(), dtype=None: jnp.ones(shape, dtype))


# -- parameter shape-inference rules (reference: per-op nnvm InferShape) ----
def _fc_shapes(ins, attrs):
    data = ins[0]
    if data is None:
        return ins
    nh = attrs.get("num_hidden")
    in_f = int(_np.prod(data[1:])) if attrs.get("flatten", True) else data[-1]
    out = [data, (nh, in_f)]
    if len(ins) == 3:
        out.append((nh,))
    return out


def _convlike_shapes(ins, attrs, weight_shape):
    """Shared data->weight/bias fill for conv-family ops;
    weight_shape(num_filter, in_c, groups, kernel, channel_first)."""
    data = ins[0]
    if data is None:
        return ins
    layout = attrs.get("layout") or {3: "NCW", 4: "NCHW",
                                     5: "NCDHW"}[len(data)]
    c = data[layout.index("C")]
    k = attrs.get("kernel")
    k = (k,) * (len(data) - 2) if isinstance(k, int) else tuple(k)
    nf, g = attrs.get("num_filter"), attrs.get("num_group", 1)
    out = [data, weight_shape(nf, c, g, k, layout.index("C") == 1)]
    if len(ins) == 3:
        out.append((nf,))
    return out


def _conv_shapes(ins, attrs):
    return _convlike_shapes(
        ins, attrs,
        lambda nf, c, g, k, cf: (nf, c // g) + k if cf
        else (nf,) + k + (c // g,))


def _norm_shapes(ins, attrs):
    data = ins[0]
    if data is None:
        return ins
    c = data[attrs.get("axis", 1) if len(data) > 1 else 0]
    return [data] + [(c,)] * (len(ins) - 1)


def _ln_shapes(ins, attrs):
    data = ins[0]
    if data is None:
        return ins
    return [data] + [(data[attrs.get("axis", -1)],)] * (len(ins) - 1)


def _embed_shapes(ins, attrs):
    return [ins[0], (attrs.get("input_dim"), attrs.get("output_dim"))]


register_shape_rule("FullyConnected", _fc_shapes)
def _deconv_shapes(ins, attrs):
    # transposed conv weight is (I, O/g, *k) in every layout (the rhs
    # spec is "IO"+spatial — see K.deconvolution)
    return _convlike_shapes(
        ins, attrs, lambda nf, c, g, k, cf: (c, nf // g) + k)


register_shape_rule("Convolution", _conv_shapes)
register_shape_rule("Deconvolution", _deconv_shapes)
register_shape_rule("StemConvS2D",
                    lambda ins, attrs: ins if ins[0] is None
                    else [ins[0], (attrs["num_filter"], 7, 7, ins[0][3])])
register_shape_rule("BatchNorm", _norm_shapes)
register_shape_rule("LayerNorm", _ln_shapes)


def _chan1_shapes(ins, attrs):
    data = ins[0]
    if data is None:
        return ins
    c = data[1] if len(data) > 1 else data[0]
    return [data] + [(c,)] * (len(ins) - 1)


register_shape_rule("InstanceNorm", _chan1_shapes)
register_shape_rule("GroupNorm", _chan1_shapes)
register_shape_rule("PReLU", _chan1_shapes)
register_shape_rule("Embedding", _embed_shapes)


# -- symbol-level API --------------------------------------------------------
def FullyConnected(data, weight=None, bias=None, num_hidden=None,
                   no_bias=False, flatten=True, name=None, **kwargs):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make("FullyConnected", ins,
                 {"no_bias": no_bias, "num_hidden": num_hidden,
                  "flatten": flatten}, name=name,
                 input_names=["data", "weight", "bias"])


def StemConvS2D(data, weight=None, num_filter=None, name=None, **kwargs):
    return _make("StemConvS2D", [data, weight], {"num_filter": num_filter},
                 name=name, input_names=["data", "weight"])


def Deconvolution(data, weight=None, bias=None, kernel=None, stride=1,
                  pad=0, adj=0, num_filter=None, no_bias=False, layout=None,
                  name=None, **kwargs):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make("Deconvolution", ins,
                 {"kernel": kernel, "stride": stride, "pad": pad,
                  "adj": adj, "num_filter": num_filter, "no_bias": no_bias,
                  "layout": layout}, name=name,
                 input_names=["data", "weight", "bias"])


def Convolution(data, weight=None, bias=None, kernel=None, stride=1, pad=0,
                dilate=1, num_filter=None, num_group=1, no_bias=False,
                layout=None, name=None, **kwargs):
    ins = [data, weight] + ([] if no_bias else [bias])
    return _make("Convolution", ins,
                 {"kernel": kernel, "stride": stride, "pad": pad,
                  "dilate": dilate, "num_filter": num_filter,
                  "num_group": num_group, "no_bias": no_bias,
                  "layout": layout}, name=name,
                 input_names=["data", "weight", "bias"])


def Activation(data, act_type="relu", name=None, **kwargs):
    return _make("Activation", [data], {"act_type": act_type}, name=name)


def BatchNorm(data, gamma=None, beta=None, moving_mean=None, moving_var=None,
              eps=1e-5, momentum=0.9, axis=1, fix_gamma=True,
              use_global_stats=False, name=None, **kwargs):
    """fix_gamma defaults True, matching the reference op (gamma pinned to
    1 unless explicitly released); gluon.nn.BatchNorm trains gamma via
    scale=True, also matching the reference Gluon layer."""
    return _make("BatchNorm", [data, gamma, beta, moving_mean, moving_var],
                 {"eps": eps, "momentum": momentum, "axis": axis,
                  "fix_gamma": fix_gamma,
                  "use_global_stats": use_global_stats}, name=name,
                 input_names=["data", "gamma", "beta", "moving_mean",
                              "moving_var"])


def LayerNorm(data, gamma=None, beta=None, axis=-1, eps=1e-5, name=None,
              **kwargs):
    return _make("LayerNorm", [data, gamma, beta],
                 {"axis": axis, "eps": eps}, name=name,
                 input_names=["data", "gamma", "beta"])


def InstanceNorm(data, gamma=None, beta=None, eps=1e-5, name=None, **kwargs):
    return _make("InstanceNorm", [data, gamma, beta], {"eps": eps},
                 name=name, input_names=["data", "gamma", "beta"])


def GroupNorm(data, gamma=None, beta=None, num_groups=1, eps=1e-5,
              name=None, **kwargs):
    return _make("GroupNorm", [data, gamma, beta],
                 {"num_groups": num_groups, "eps": eps}, name=name,
                 input_names=["data", "gamma", "beta"])


def PReLU(data, alpha=None, name=None, **kwargs):
    return _make("PReLU", [data, alpha], {}, name=name,
                 input_names=["data", "alpha"])


def Pooling(data, kernel=None, pool_type="max", stride=None, pad=0,
            global_pool=False, layout=None, count_include_pad=True,
            name=None, **kwargs):
    return _make("Pooling", [data],
                 {"kernel": kernel, "pool_type": pool_type, "stride": stride,
                  "pad": pad, "global_pool": global_pool, "layout": layout,
                  "count_include_pad": count_include_pad},
                 name=name)


def Dropout(data, p=0.5, name=None, **kwargs):
    return _make("Dropout", [data], {"p": p}, name=name)


def Embedding(data, weight=None, input_dim=None, output_dim=None, name=None,
              **kwargs):
    return _make("Embedding", [data, weight],
                 {"input_dim": input_dim, "output_dim": output_dim},
                 name=name, input_names=["data", "weight"])


def SoftmaxOutput(data, label=None, use_ignore=False, ignore_label=-1,
                  normalization="null", grad_scale=1.0, name=None,
                  **kwargs):
    if normalization not in ("null", "valid", "batch"):
        raise MXNetError(f"SoftmaxOutput normalization must be "
                         f"null/valid/batch, got {normalization!r}")
    ins = [data] if label is None else [data, label]
    return _make("SoftmaxOutput", ins,
                 {"use_ignore": use_ignore, "ignore_label": ignore_label,
                  "normalization": normalization,
                  "grad_scale": grad_scale}, name=name)


def LinearRegressionOutput(data, label=None, grad_scale=1.0, name=None,
                           **kwargs):
    ins = [data] if label is None else [data, label]
    return _make("LinearRegressionOutput", ins,
                 {"grad_scale": grad_scale}, name=name)


def MAERegressionOutput(data, label=None, grad_scale=1.0, name=None,
                        **kwargs):
    ins = [data] if label is None else [data, label]
    return _make("MAERegressionOutput", ins,
                 {"grad_scale": grad_scale}, name=name)


def LogisticRegressionOutput(data, label=None, grad_scale=1.0, name=None,
                             **kwargs):
    ins = [data] if label is None else [data, label]
    return _make("LogisticRegressionOutput", ins,
                 {"grad_scale": grad_scale}, name=name)


def softmax(data, length=None, axis=-1, use_length=False, causal=False,
            name=None):
    if length is not None or use_length:
        if length is None:
            raise MXNetError("softmax: use_length=True needs a length input")
        return _make("softmax", [data, length],
                     {"axis": axis, "use_length": True, "causal": causal},
                     name=name)
    if causal:
        return _make("softmax", [data], {"axis": axis, "causal": True},
                     name=name)
    return _make("softmax", [data], {"axis": axis}, name=name)


def log_softmax(data, axis=-1, name=None):
    return _make("log_softmax", [data], {"axis": axis}, name=name)


def flatten(data, name=None, **kwargs):
    return _make("flatten", [data], {}, name=name)


Flatten = flatten


def reshape(data, shape, name=None, **kwargs):
    return _make("reshape", [data], {"shape": tuple(shape)}, name=name)


def transpose(data, axes=None, name=None):
    return _make("transpose", [data], {"axes": axes}, name=name)


def concat(*data, dim=1, name=None, **kwargs):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return _make("concat", list(data), {"dim": dim}, name=name)


Concat = concat


def dot(lhs, rhs, name=None, **kwargs):
    return _make("dot", [lhs, rhs], {}, name=name)


def batch_dot(lhs, rhs, name=None, **kwargs):
    return _make("batch_dot", [lhs, rhs], {}, name=name)


def sum(data, axis=None, keepdims=False, name=None):
    return _make("sum", [data], {"axis": axis, "keepdims": keepdims}, name=name)


def mean(data, axis=None, keepdims=False, name=None):
    return _make("mean", [data], {"axis": axis, "keepdims": keepdims},
                 name=name)


def max(data, axis=None, keepdims=False, name=None):
    return _make("max", [data], {"axis": axis, "keepdims": keepdims}, name=name)


def min(data, axis=None, keepdims=False, name=None):
    return _make("min", [data], {"axis": axis, "keepdims": keepdims}, name=name)


def _slice_kernel(a, begin=(), end=(), step=None):
    import builtins
    step = step or [None] * len(begin)
    # builtins.slice: the symbolic `slice` op shadows the name below
    idx = tuple(builtins.slice(b, e, s)
                for b, e, s in zip(begin, end, step))
    return a[idx]


register_op("slice", _slice_kernel)
register_op("slice_axis",
            lambda a, axis=0, begin=0, end=None:
            jax.lax.slice_in_dim(a, begin, a.shape[axis] if end is None
                                 else (end if end >= 0
                                       else a.shape[axis] + end),
                                 axis=axis))


def slice(data, begin, end, step=None, name=None):  # noqa: A001
    return _make("slice", [data],
                 {"begin": tuple(begin), "end": tuple(end),
                  "step": tuple(step) if step else None}, name=name)


def slice_axis(data, axis, begin, end, name=None):
    return _make("slice_axis", [data],
                 {"axis": axis, "begin": begin, "end": end}, name=name)


def expand_dims(data, axis, name=None):
    return _make("expand_dims", [data], {"axis": axis}, name=name)


def squeeze(data, axis=None, name=None):
    return _make("squeeze", [data], {"axis": axis}, name=name)


def broadcast_add(lhs, rhs, name=None):
    return _make("elemwise_add", [lhs, rhs], {}, name=name)


def broadcast_mul(lhs, rhs, name=None):
    return _make("elemwise_mul", [lhs, rhs], {}, name=name)


def _broadcast_cmp(opname):
    def f(lhs, rhs, name=None):
        return _make(opname, [lhs, rhs], {}, name=name)
    f.__name__ = opname
    return f


broadcast_lesser = _broadcast_cmp("broadcast_lesser")
broadcast_lesser_equal = _broadcast_cmp("broadcast_lesser_equal")
broadcast_greater = _broadcast_cmp("broadcast_greater")
broadcast_greater_equal = _broadcast_cmp("broadcast_greater_equal")


elemwise_add = broadcast_add


def _unary(opname):
    def f(data, name=None, **kwargs):
        return _make(opname, [data], {}, name=name)
    f.__name__ = opname
    return f


relu = _unary("relu")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
exp = _unary("exp")
log = _unary("log")
sqrt = _unary("sqrt")
square = _unary("square")
negative = _unary("negative")


def zeros(shape, dtype=None, name=None, **kwargs):
    return _make("zeros", [], {"shape": tuple(shape), "dtype": dtype},
                 name=name)


def ones(shape, dtype=None, name=None, **kwargs):
    return _make("ones", [], {"shape": tuple(shape), "dtype": dtype},
                 name=name)


# -- custom ops in symbol graphs (reference: mx.sym.Custom / custom.cc) -----
def _custom_eval(*args, _train=False, op_type=None, **prop_kwargs):
    from ..operator import _build_custom_fn
    in_shapes = [tuple(a.shape) for a in args]
    fn, _, _ = _build_custom_fn(op_type, prop_kwargs, in_shapes,
                                train=_train)
    return fn(*args)


register_op("_custom", _custom_eval)
register_train_op(
    "_custom",
    lambda *args, _rng=None, **kw: (_custom_eval(*args, _train=True, **kw),
                                    {}))


def _custom_shapes(ins, attrs):
    """Let CustomOpProp.infer_shape fill unknown input shapes (reference:
    custom-op shape inference completes weight shapes). The prop receives
    the partially-known list (None for unknowns) and returns the
    completed input shapes as its first element. An unregistered op_type
    propagates (loading a graph requires re-registering its custom ops);
    only a prop that cannot handle partial shapes falls back."""
    from ..operator import get as _get_custom
    kw = {k: v for k, v in attrs.items() if k != "op_type"}
    prop = _get_custom(attrs["op_type"])(**kw)  # raises if unregistered
    try:
        return list(prop.infer_shape(list(ins))[0])
    except (TypeError, ValueError, AttributeError, IndexError):
        return ins  # prop cannot handle partial shapes: leave unknown


register_shape_rule("_custom", _custom_shapes)


def Custom(*inputs, op_type=None, name=None, **prop_kwargs):
    """Place a registered CustomOp in a symbol graph (reference:
    mx.sym.Custom). Shapes/arity come from the registered CustomOpProp;
    attrs are plain JSON values, so the graph round-trips through
    symbol.json (the op must be registered again at load time, like the
    reference)."""
    from ..operator import _prop_for
    prop = _prop_for(op_type, prop_kwargs, len(inputs))
    return _make("_custom", list(inputs),
                 {"op_type": op_type, **prop_kwargs}, name=name,
                 n_out=len(prop.list_outputs()))


# -- classic extra ops (reference: lrn.cc, l2_normalization.cc, ...) --------
from ..ops import extra_ops as _extra

register_op("LRN", lambda x, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5:
            _extra.lrn_k(x, alpha, beta, knorm, nsize))
register_op("L2Normalization", lambda x, eps=1e-10, mode="instance":
            _extra.l2_normalization_k(x, eps, mode))
register_op("UpSampling", lambda x, scale=2, sample_type="nearest",
            num_filter=0: _extra.upsampling_k(x, scale, sample_type))
register_op("BlockGrad", jax.lax.stop_gradient)
register_op("MakeLoss", lambda x, grad_scale=1.0:
            _extra.make_loss_k(x, grad_scale))
register_op("SliceChannel",
            lambda x, num_outputs=1, axis=1, squeeze_axis=False:
            tuple(jnp.squeeze(p, axis=axis) if squeeze_axis else p
                  for p in jnp.split(x, num_outputs, axis=axis)))


def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, name=None):
    return _make("LRN", [data], {"alpha": alpha, "beta": beta,
                                 "knorm": knorm, "nsize": nsize}, name=name)


def L2Normalization(data, eps=1e-10, mode="instance", name=None):
    return _make("L2Normalization", [data], {"eps": eps, "mode": mode},
                 name=name)


def UpSampling(data, scale=2, sample_type="nearest", num_filter=0,
               name=None, **kwargs):
    return _make("UpSampling", [data],
                 {"scale": scale, "sample_type": sample_type}, name=name)


def BlockGrad(data, name=None):
    return _make("BlockGrad", [data], {}, name=name)


stop_gradient = BlockGrad


def MakeLoss(data, grad_scale=1.0, name=None, **kwargs):
    return _make("MakeLoss", [data], {"grad_scale": grad_scale}, name=name)


def SliceChannel(data, num_outputs=1, axis=1, squeeze_axis=False,
                 name=None):
    return _make("SliceChannel", [data],
                 {"num_outputs": num_outputs, "axis": axis,
                  "squeeze_axis": squeeze_axis}, name=name,
                 n_out=num_outputs)


split = SliceChannel


# -- cast / indexing (reference: tensor cast + take ops) --------------------
register_op("cast", lambda x, dtype="float32": x.astype(dtype))
def _take_kernel(a, *maybe_idx, axis=0, mode="clip", indices=None):
    # `indices` as an ATTR (no second input) keeps the gather concrete
    # when `a` is itself concrete (numpy) under jit tracing — the ONNX
    # importer inlines constant indices this way so Shape->Gather->Range
    # mask chains fold at trace time instead of failing on a traced arange
    m = {"clip": "clip", "wrap": "wrap"}.get(mode, "clip")
    if not maybe_idx and isinstance(a, _np.ndarray):
        return _np.take(a, _np.asarray(indices), axis=axis, mode=m)
    idx = maybe_idx[0] if maybe_idx else jnp.asarray(indices)
    if hasattr(idx, "astype"):
        idx = idx.astype(jnp.int32)
    return jnp.take(a, idx, axis=axis, mode=m)


register_op("take", _take_kernel)
register_op("abs", jnp.abs)


def cast(data, dtype="float32", name=None):
    return _make("cast", [data], {"dtype": dtype}, name=name)


Cast = cast


def take(a, indices, axis=0, mode="clip", name=None):
    return _make("take", [a, indices], {"axis": axis, "mode": mode},
                 name=name)


# -- sequence ops (reference: src/operator/sequence_*.cc) -------------------
from ..ops import seq_ops as _seq

register_op("SequenceMask",
            lambda *ins, use_sequence_length=False, value=0.0, axis=0:
            _seq.sequence_mask_k(ins[0],
                                 ins[1] if use_sequence_length else None,
                                 value=value, axis=axis))
register_op("SequenceLast",
            lambda *ins, use_sequence_length=False, axis=0:
            _seq.sequence_last_k(ins[0],
                                 ins[1] if use_sequence_length else None,
                                 axis=axis))
register_op("SequenceReverse",
            lambda *ins, use_sequence_length=False, axis=0:
            _seq.sequence_reverse_k(ins[0],
                                    ins[1] if use_sequence_length else None,
                                    axis=axis))
register_op("smooth_l1",
            lambda x, scalar=1.0: _seq.smooth_l1_k(x, scalar=scalar))
register_op("softmin", lambda x, axis=-1: _seq.softmin_k(x, axis=axis))
register_op("hard_sigmoid",
            lambda x, alpha=0.2, beta=0.5:
            _seq.hard_sigmoid_k(x, alpha=alpha, beta=beta))


def _seq_inputs(data, sequence_length, use_sequence_length):
    try:
        return _seq._seq_args(data, sequence_length, use_sequence_length)
    except ValueError as e:
        raise MXNetError(str(e)) from None


def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0, name=None):
    return _make("SequenceMask",
                 _seq_inputs(data, sequence_length, use_sequence_length),
                 {"use_sequence_length": use_sequence_length,
                  "value": value, "axis": axis}, name=name)


def SequenceLast(data, sequence_length=None, use_sequence_length=False,
                 axis=0, name=None):
    return _make("SequenceLast",
                 _seq_inputs(data, sequence_length, use_sequence_length),
                 {"use_sequence_length": use_sequence_length, "axis": axis},
                 name=name)


def SequenceReverse(data, sequence_length=None, use_sequence_length=False,
                    axis=0, name=None):
    return _make("SequenceReverse",
                 _seq_inputs(data, sequence_length, use_sequence_length),
                 {"use_sequence_length": use_sequence_length, "axis": axis},
                 name=name)


def smooth_l1(data, scalar=1.0, name=None):
    return _make("smooth_l1", [data], {"scalar": scalar}, name=name)


def softmin(data, axis=-1, name=None):
    return _make("softmin", [data], {"axis": axis}, name=name)


def hard_sigmoid(data, alpha=0.2, beta=0.5, name=None):
    return _make("hard_sigmoid", [data], {"alpha": alpha, "beta": beta},
                 name=name)


# -- fused RNN layers as one symbol node (reference: sym.RNN / rnn-inl.h) ---
def _rnn_eval(x, *rest, mode="lstm", num_layers=1, num_dir=1,
              hidden_size=0, layout_ntc=False, pnames=(),
              state_outputs=False, use_sequence_length=False, dropout=0.0,
              _rng=None):
    from ..gluon.rnn.rnn_layer import rnn_forward
    ns = 2 if mode == "lstm" else 1
    seq_len = None
    if use_sequence_length:
        seq_len, rest = rest[0], rest[1:]
    if state_outputs:
        svals, pvals = rest[:ns], rest[ns:]
    else:
        batch = x.shape[0] if layout_ntc else x.shape[1]
        zero = jnp.zeros((num_layers * num_dir, batch, hidden_size),
                         x.dtype)
        svals, pvals = (zero,) * ns, rest
    return rnn_forward(mode, num_layers, num_dir, layout_ntc, pnames,
                       x, svals, pvals, dropout=dropout, rng=_rng,
                       seq_len=seq_len)


register_op("RNN", _rnn_eval)
# training: inter-layer dropout keyed off the Executor's step rng
register_train_op("RNN", lambda *a, _rng=None, **kw:
                  (_rnn_eval(*a, _rng=_rng, **kw), {}))


def _rnn_shapes(ins, attrs):
    data = ins[0]
    if data is None:
        return ins
    mode = attrs.get("mode", "lstm")
    L, D = attrs.get("num_layers", 1), attrs.get("num_dir", 1)
    H = attrs.get("hidden_size")
    g = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]
    ns = (2 if mode == "lstm" else 1) if attrs.get("state_outputs") else 0
    batch = data[0] if attrs.get("layout_ntc") else data[1]
    in_size = data[-1]
    out = [data] + \
        ([(batch,)] if attrs.get("use_sequence_length") else []) + \
        [(L * D, batch, H)] * ns
    for name in attrs.get("pnames", ()):
        layer = int(name.split("_")[0][1:])
        if name.endswith("i2h_weight"):
            out.append((g * H, in_size if layer == 0 else H * D))
        elif name.endswith("h2h_weight"):
            out.append((g * H, H))
        else:
            out.append((g * H,))
    return out


register_shape_rule("RNN", _rnn_shapes)


def RNN(data, *state_and_params, mode="lstm", num_layers=1, num_dir=1,
        hidden_size=0, layout_ntc=False, pnames=(), state_outputs=False,
        use_sequence_length=False, dropout=0.0, name=None):
    """Fused multi-layer (bi)RNN node (reference: mx.sym.RNN): one lax.scan
    stack per layer/direction compiled inside the Executor's program. With
    use_sequence_length=True the first extra input (after data) is the (N,)
    sequence_length vector (reference rnn-inl.h variable-length path)."""
    ns = (2 if mode == "lstm" else 1)
    return _make("RNN", [data] + list(state_and_params),
                 {"mode": mode, "num_layers": num_layers,
                  "num_dir": num_dir, "hidden_size": hidden_size,
                  "layout_ntc": layout_ntc, "pnames": tuple(pnames),
                  "state_outputs": state_outputs,
                  "use_sequence_length": use_sequence_length,
                  "dropout": dropout},
                 name=name, n_out=1 + ns)


# --------------------------------------------------------------------------
# dynamic-shape helpers (reference: mx.sym.shape_array, mx.sym.where —
# src/operator/tensor/elemwise_unary_op_basic.cc, control_flow_op.cc).
# These also let the ONNX importer rebuild the exporter's dynamic
# attention-mask idiom (Shape -> Range -> Less -> Where) eagerly.
# NUMPY output on purpose: a shape is static under jit, and keeping the
# value out of jnp (which lifts constants into tracers at trace time)
# lets Shape->Gather->Range chains fold to Python ints — the ONNX
# importer's dynamic attention mask relies on this
# zero initial RNN state derived from a graph tensor: 0 in `shape`
# marks the batch dim, filled from the like-input's leading axis at
# trace time (the legacy rnn_cell.begin_state path — upstream uses
# sym.zeros with shape=(0, H) and nnvm back-infers the 0; our executor
# traces concrete shapes, so the batch rides the graph instead)
register_op("_rnn_zero_state",
            lambda x, shape=(), batch_axis=0: jnp.zeros(
                tuple(x.shape[batch_axis] if s == 0 else s for s in shape),
                x.dtype))
register_op("_rnn_ones_like", jnp.ones_like)

register_op("shape_array", lambda a: _np.asarray(a.shape, _np.int32))
register_op("where", lambda c, a, b: jnp.where(c != 0, a, b))
# arange whose limit arrives as a (scalar) graph INPUT, not an attr.
# Executable when the limit is concrete: eagerly, or under jit when it
# folds from static shapes (shape_array output is concrete at trace
# time); a genuinely data-dependent limit is a dynamic shape and raises.
register_op("_dynamic_arange",
            lambda l, start=0, delta=1:
            jnp.arange(int(start), int(_np.asarray(l).reshape(-1)[0]),
                       int(delta)))


def shape_array(data, name=None):
    return _make("shape_array", [data], {}, name=name)


def where(condition, x, y, name=None):
    return _make("where", [condition, x, y], {}, name=name)


def _dynamic_arange(limit, start=0, delta=1, name=None):
    return _make("_dynamic_arange", [limit],
                 {"start": start, "delta": delta}, name=name)


# -- indexing/selection mirrors of the nd surface (VERDICT-style probe
# gaps, round 5): one_hot, topk, pick, gather_nd, slice_like,
# broadcast_axis, masked_softmax, SVMOutput -------------------------------
def _one_hot_eval(idx, depth=0, on_value=1.0, off_value=0.0,
                  dtype=None):
    oh = jax.nn.one_hot(idx.astype(jnp.int32), int(depth))
    out = oh * (on_value - off_value) + off_value
    return out.astype(_np_dtype(dtype) if dtype else jnp.float32)


register_op("one_hot", _one_hot_eval)


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype=None,
            name=None):
    return _make("one_hot", [indices],
                 {"depth": int(depth), "on_value": on_value,
                  "off_value": off_value, "dtype": dtype}, name=name)


def _topk_eval(x, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    if ret_typ not in ("indices", "value", "both", "mask"):
        raise MXNetError(f"topk: unknown ret_typ {ret_typ!r}")
    v = -x if not is_ascend else x
    vals, idx = jax.lax.top_k(jnp.moveaxis(-v, axis, -1), int(k))
    # lax.top_k takes the LARGEST of (-v) = smallest of v when ascending
    if ret_typ == "mask":
        # same-shape 0/1 mask of the selected entries (reference mode)
        moved = jnp.moveaxis(x, axis, -1)
        mask = jnp.zeros_like(moved).at[
            (*jnp.indices(idx.shape[:-1], sparse=True), idx)].set(1.0)
        return jnp.moveaxis(mask, -1, axis)
    vals = jnp.moveaxis(vals if not is_ascend else -vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(jnp.float32)
    return idx.astype(jnp.float32)  # reference returns float indices


register_op("topk", _topk_eval)


def topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False,
         name=None):
    return _make("topk", [data],
                 {"k": int(k), "axis": axis, "ret_typ": ret_typ,
                  "is_ascend": bool(is_ascend)}, name=name,
                 n_out=2 if ret_typ == "both" else 1)


register_op("pick",
            lambda x, i, axis=-1, keepdims=False:
            (jnp.take_along_axis(x, i.astype(jnp.int32)[..., None]
                                 if i.ndim == x.ndim - 1 else
                                 i.astype(jnp.int32), axis)
             if keepdims else
             jnp.squeeze(jnp.take_along_axis(
                 x, i.astype(jnp.int32)[..., None]
                 if i.ndim == x.ndim - 1 else i.astype(jnp.int32),
                 axis), axis)))


def pick(data, index, axis=-1, keepdims=False, name=None):
    return _make("pick", [data, index],
                 {"axis": axis, "keepdims": bool(keepdims)}, name=name)


register_op("gather_nd",
            lambda a, i: a[tuple(i.astype(jnp.int32))])


def gather_nd(data, indices, name=None):
    return _make("gather_nd", [data, indices], {}, name=name)


def _slice_like_eval(a, b, axes=None):
    import builtins
    axes_ = axes if axes else tuple(range(b.ndim))
    idx = [builtins.slice(None)] * a.ndim
    for ax in axes_:
        idx[ax] = builtins.slice(0, b.shape[ax])
    return a[tuple(idx)]


register_op("slice_like", _slice_like_eval)


def slice_like(data, shape_like, axes=None, name=None):
    return _make("slice_like", [data, shape_like],
                 {"axes": tuple(axes) if axes else None}, name=name)


def _broadcast_axis_eval(a, axis=0, size=1):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    sizes = size if isinstance(size, (list, tuple)) else [size]
    shape = list(a.shape)
    for ax, s in zip(axes, sizes):
        shape[ax] = s
    return jnp.broadcast_to(a, tuple(shape))


register_op("broadcast_axis", _broadcast_axis_eval)


def broadcast_axis(data, axis=0, size=1, name=None):
    return _make("broadcast_axis", [data],
                 {"axis": axis, "size": size}, name=name)


from ..ops.tensor_ops import masked_softmax_k as _masked_softmax_k

register_op("masked_softmax", _masked_softmax_k)


def masked_softmax(data, mask, axis=-1, temperature=1.0, name=None):
    """reference: masked_softmax (softmax.cc) — masked-off positions get
    exactly 0 probability."""
    return _make("masked_softmax", [data, mask],
                 {"axis": axis, "temperature": temperature}, name=name)


from ..ops.compat_ops import svm_output_k as _svm_k

register_op("SVMOutput", lambda x, y=None, margin=1.0,
            regularization_coefficient=1.0, use_linear=False:
            x if y is None else _svm_k(
                x, y, margin, regularization_coefficient, use_linear))


def SVMOutput(data, label=None, margin=1.0,
              regularization_coefficient=1.0, use_linear=False,
              name=None, **kw):
    """reference: svm_output.cc — identity forward, hinge-loss backward."""
    ins = [data] if label is None else [data, label]
    return _make("SVMOutput", ins,
                 {"margin": margin,
                  "regularization_coefficient": regularization_coefficient,
                  "use_linear": use_linear}, name=name)


__all__ += ["one_hot", "topk", "pick", "gather_nd", "slice_like",
            "broadcast_axis", "masked_softmax", "SVMOutput"]


# -- classic spatial extra ops, sym side (wave 4: upstream registers
# these under both namespaces; nd side lives in ops/extra_ops.py) ---------
from ..ops import extra_ops as _xtra

from ..ops.tensor_ops import functools_reduce as _fold_add

register_op("add_n", lambda *xs: _fold_add(xs))   # one n-ary-add impl
register_op("Crop",
            lambda x, *like, h_w=None, offset=(0, 0), center_crop=False:
            _xtra.crop_k(x, like_shape=like[0].shape, offset=offset,
                         center_crop=center_crop) if like else
            _xtra.crop_k(x, h_w=h_w, offset=offset,
                         center_crop=center_crop))
register_op("ROIPooling",
            lambda x, rois, pooled_size=(7, 7), spatial_scale=1.0:
            _xtra.roi_pooling_k(x, rois, tuple(pooled_size),
                                spatial_scale))
register_op("GridGenerator",
            lambda a, target_shape=None:
            _xtra.grid_generator_k(a, tuple(target_shape)))
register_op("BilinearSampler", _xtra.bilinear_sampler_k)
register_op("SpatialTransformer",
            lambda x, a, target_shape=None:
            _xtra.spatial_transformer_k(x, a, tuple(target_shape)))
register_op("Correlation",
            lambda a, b, kernel_size=1, max_displacement=4, stride1=1,
            stride2=1, is_multiply=True:
            _xtra.correlation_k(a, b, kernel_size=kernel_size,
                                max_displacement=max_displacement,
                                stride1=stride1, stride2=stride2,
                                is_multiply=is_multiply))

from ..ops.compat_ops import _im2col_fn as _im2col_k
from ..ops.compat_ops import _norm2 as _normN


def _im2col_eval(x, kernel=None, stride=1, dilate=1, pad=0):
    nsp = x.ndim - 2          # spatial dims from the DATA, like nd side
    return _im2col_k(x, _normN(kernel, nsp), _normN(stride, nsp),
                     _normN(dilate, nsp), _normN(pad, nsp))


register_op("im2col", _im2col_eval)


def add_n(*args, name=None):
    return _make("add_n", list(args), {}, name=name)


def Crop(data, crop_like=None, h_w=None, offset=(0, 0),
         center_crop=False, name=None, **kw):
    if crop_like is None and h_w is None:
        raise MXNetError("Crop: need crop_like or h_w")
    ins = [data] + ([crop_like] if crop_like is not None else [])
    return _make("Crop", ins,
                 {"h_w": h_w, "offset": tuple(offset),
                  "center_crop": center_crop}, name=name)


def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
               name=None, **kw):
    return _make("ROIPooling", [data, rois],
                 {"pooled_size": tuple(pooled_size),
                  "spatial_scale": spatial_scale}, name=name)


def GridGenerator(data, transform_type="affine", target_shape=None,
                  name=None, **kw):
    if transform_type != "affine":
        raise MXNetError("GridGenerator: only affine mode")
    if target_shape is None:
        raise MXNetError("GridGenerator: target_shape is required")
    return _make("GridGenerator", [data],
                 {"target_shape": tuple(target_shape)}, name=name)


def BilinearSampler(data, grid, name=None, **kw):
    return _make("BilinearSampler", [data, grid], {}, name=name)


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine",
                       sampler_type="bilinear", name=None, **kw):
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer: affine+bilinear only")
    if target_shape is None:
        raise MXNetError("SpatialTransformer: target_shape is required")
    return _make("SpatialTransformer", [data, loc],
                 {"target_shape": tuple(target_shape)}, name=name)


def Correlation(data1, data2, kernel_size=1, max_displacement=4,
                stride1=1, stride2=1, is_multiply=True, name=None, **kw):
    return _make("Correlation", [data1, data2],
                 {"kernel_size": kernel_size,
                  "max_displacement": max_displacement, "stride1": stride1,
                  "stride2": stride2, "is_multiply": is_multiply},
                 name=name)


def im2col(data, kernel, stride=1, dilate=1, pad=0, name=None, **kw):
    return _make("im2col", [data],
                 {"kernel": kernel if isinstance(kernel, int)
                  else tuple(kernel), "stride": stride,
                  "dilate": dilate, "pad": pad}, name=name)


__all__ += ["add_n", "Crop", "ROIPooling", "GridGenerator",
            "BilinearSampler", "SpatialTransformer", "Correlation",
            "im2col"]


register_op("ones_like", jnp.ones_like)
register_op("zeros_like", jnp.zeros_like)
register_op("full", lambda shape=(), val=0.0, dtype=None:
            jnp.full(tuple(shape), val,
                     _np_dtype(dtype) if dtype else jnp.float32))


def ones_like(data, name=None):
    return _make("ones_like", [data], {}, name=name)


def zeros_like(data, name=None):
    return _make("zeros_like", [data], {}, name=name)


def full(shape, val, dtype=None, name=None, **kw):
    return _make("full", [], {"shape": tuple(shape), "val": val,
                              "dtype": dtype}, name=name)


__all__ += ["ones_like", "zeros_like", "full"]
