"""TrainingSupervisor unit tests (ISSUE 10 tentpole): failure
classification, replay cursor determinism, per-domain recovery policies,
restart-budget escalation, crash report, resume. The full cross-domain
soak (bitwise parity, leaks) runs in tests/test_check_resilience.py."""
import json
import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, engine, fault, gluon, kvstore, nd
from mxnet_tpu.fault.supervisor import _ReplayCursor
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import registry


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fault.clear()
    fault.reset_preemption(clear_callbacks=True)
    fault.uninstall_preemption_handler()
    fault.watchdog.set_default(None)
    engine.clear_failures()


def _build(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=16),
            nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    net(nd.zeros((1, 16)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore="ici", fused=False)
    return net, tr


def _data(n=5, seed=0):
    rng = np.random.RandomState(seed)
    return [(nd.array(rng.randn(4, 16).astype(np.float32)),
             nd.array(rng.randint(0, 4, 4).astype(np.float32)))
            for _ in range(n)]


_lossf = gluon.loss.SoftmaxCrossEntropyLoss()


def _step(net, tr):
    def step(batch):
        x, y = batch
        with autograd.record():
            loss = _lossf(net(x), y).mean()
        loss.backward()
        tr.step(x.shape[0])
        return loss
    return step


def _params(net):
    return [np.asarray(p.data().asnumpy())
            for p in net.collect_params().values()]


# ------------------------------------------------------- classification
def test_classify_failure_table():
    cf = fault.classify_failure
    assert cf(fault.Preempted("x")) == "preemption"
    assert cf(fault.DeviceLost(3)) == "capacity_loss"
    assert cf(fault.WatchdogTimeout("x")) == "hang"
    assert cf(kvstore.CollectiveTimeout("allreduce", 100)) == "hang"
    assert cf(fault.NonFiniteLoss("x")) == "corrupt_state"
    assert cf(fault.DivergedLoss("x")) == "corrupt_state"
    assert cf(fault.FaultInjected("io.read")) == "transient"
    assert cf(OSError("disk")) == "transient"
    assert cf(RuntimeError("?")) == "transient"


# -------------------------------------------------------- replay cursor
def test_replay_cursor_factory_seek_is_deterministic():
    data = list(range(7))
    cur = _ReplayCursor(lambda: iter(data))
    first = [cur.next() for _ in range(10)]   # wraps the epoch at 7
    cur.seek(4)
    assert cur.drawn == 4
    assert [cur.next() for _ in range(6)] == first[4:10]


def test_replay_cursor_reiterable_and_one_shot():
    cur = _ReplayCursor([1, 2, 3])            # re-iterable: replayable
    assert [cur.next() for _ in range(4)] == [1, 2, 3, 1]
    cur.seek(0)
    assert cur.next() == 1
    one = _ReplayCursor(iter([1, 2]))         # bare iterator: trainable...
    assert one.next() == 1
    with pytest.raises(mx.base.MXNetError):   # ...but seek refuses
        one.seek(0)


# -------------------------------------------------- per-domain policies
def test_divergence_detection_rolls_back(tmp_path):
    """A loss explosion (not NaN) triggers the corrupt-state policy via
    DivergedLoss."""
    net, tr = _build()
    data = _data()
    calls = {"n": 0}
    real = _step(net, tr)

    def step(batch):
        loss = real(batch)
        calls["n"] += 1
        if calls["n"] == 6:
            return nd.array([1e9])            # diverged, finite
        return loss

    rep, sup = fault.run_supervised(
        tr, step, lambda: iter(data), 8,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        backoff_base=0.0, emergency_save=False, divergence_factor=100.0)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["corrupt_state"] >= 1
    assert any("DivergedLoss" in i["error"] for i in rep["incidents"]
               if i["domain"] == "corrupt_state")


def test_transient_retries_do_not_consume_budget(tmp_path):
    r0 = registry().counter("fault_recoveries", domain="transient").value
    fault.inject("kv.collective", at=[5])     # one mid-step raise
    net, tr = _build()
    rep, sup = fault.run_supervised(
        tr, _step(net, tr), lambda: iter(_data()), 6,
        checkpoint_dir=str(tmp_path / "ck"), backoff_base=0.0,
        emergency_save=False)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["transient"] == 1
    assert rep["budget_remaining"] == sup.restart_budget   # untouched
    assert registry().counter("fault_recoveries",
                              domain="transient").value == r0 + 1


def test_rollback_restores_optimizer_state(tmp_path):
    """Momentum state must ride the rollback: after recovery the params
    are bitwise-equal to a fault-free run (which only holds if momentum
    was restored too)."""
    data = _data()
    net, tr = _build()
    fault.clear()
    rep, _ = fault.run_supervised(tr, _step(net, tr), lambda: iter(data),
                                  8, checkpoint_dir=None,
                                  emergency_save=False)
    clean = _params(net)
    fault.inject("grad.nan", at=[5])
    net, tr = _build()
    rep, _ = fault.run_supervised(
        tr, _step(net, tr), lambda: iter(data), 8,
        checkpoint_dir=str(tmp_path / "ck2"), checkpoint_every=2,
        backoff_base=0.0, emergency_save=False)
    assert rep["recoveries"]["corrupt_state"] == 1
    assert all(np.array_equal(a, b) for a, b in zip(clean, _params(net)))


def test_budget_exhaustion_crash_report(tmp_path):
    fault.inject("grad.nan", prob=1.0)
    net, tr = _build()
    with pytest.raises(fault.RecoveryExhausted) as ei:
        fault.run_supervised(
            tr, _step(net, tr), lambda: iter(_data()), 10,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
            restart_budget=2, backoff_base=0.0, emergency_save=False,
            crash_dir=str(tmp_path / "crash"))
    fault.clear()
    e = ei.value
    assert e.report["reason"] == "restart budget exhausted"
    assert len(e.report["incidents"]) >= 3    # 2 recovered + the fatal one
    assert e.report_path and os.path.exists(e.report_path)
    blob = json.load(open(e.report_path))
    assert blob["domain"] == "corrupt_state"
    assert "metrics" in blob and "engine_pending" in blob
    assert registry().gauge("fault_restart_budget_remaining").value == 0


def test_budget_restores_after_clean_progress(tmp_path):
    """budget_reset_steps of clean progress refills the restart budget —
    two incidents separated by a long quiet stretch never exhaust a
    budget of 1."""
    fault.inject("grad.nan", at=[3, 14])
    net, tr = _build()
    rep, sup = fault.run_supervised(
        tr, _step(net, tr), lambda: iter(_data()), 18,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        restart_budget=1, budget_reset_steps=4, backoff_base=0.0,
        emergency_save=False)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["corrupt_state"] == 2
    assert rep["budget_remaining"] >= 0


def test_unwritable_crash_dir_still_raises_structured(tmp_path):
    """Crash-only to the end: an unwritable crash dir degrades to the
    in-exception report — never a secondary crash."""
    blocker = tmp_path / "f"
    blocker.write_text("x")
    fault.inject("grad.nan", prob=1.0)
    net, tr = _build()
    with pytest.raises(fault.RecoveryExhausted) as ei:
        fault.run_supervised(
            tr, _step(net, tr), lambda: iter(_data()), 10,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
            restart_budget=1, backoff_base=0.0, emergency_save=False,
            crash_dir=str(blocker / "sub"))
    fault.clear()
    assert ei.value.report_path is None
    assert ei.value.report["reason"] == "restart budget exhausted"


def test_resume_auto_detects_existing_checkpoints(tmp_path):
    data = _data()
    net, tr = _build()
    rep, _ = fault.run_supervised(tr, _step(net, tr), lambda: iter(data),
                                  6, checkpoint_dir=str(tmp_path / "ck"),
                                  checkpoint_every=3, emergency_save=False)
    assert rep["outcome"] == "completed"
    # second supervisor over the same dir resumes instead of restarting
    net2, tr2 = _build(seed=99)
    rep2, _ = fault.run_supervised(tr2, _step(net2, tr2),
                                   lambda: iter(data), 10,
                                   checkpoint_dir=str(tmp_path / "ck"),
                                   checkpoint_every=3,
                                   emergency_save=False)
    assert rep2["resumed_from"] == 6
    assert rep2["applied"] == 10


def test_health_record_and_step_failure_metric(tmp_path):
    """The health record reflects the rolling window, and a captured-
    step death shows up in cachedop_step_failures{kind=}."""
    net, tr = _build()
    sup = fault.TrainingSupervisor(tr, _step(net, tr),
                                   lambda: iter(_data()),
                                   checkpoint_dir=str(tmp_path / "ck"),
                                   emergency_save=False)
    sup._losses = [1.0, 0.9, float("nan")]
    h = sup.health_record()
    assert h["finite"] is False and h["healthy"] is False
    sup._losses = [1.0, 1.1, 0.9, 1.0, 1.05]
    assert sup.health_record()["healthy"] is True
    # poisoned PARAMS with a clean loss window: the journal must still
    # flag the save unhealthy (params_finite)
    p0 = next(iter(net.collect_params().values()))
    keep = np.asarray(p0.data().asnumpy())
    p0.set_data(nd.array(keep * np.nan))
    h = sup.health_record()
    assert h["params_finite"] is False and h["healthy"] is False
    p0.set_data(nd.array(keep))
    # captured-step failure surfacing (the fault fires INSIDE the step)
    c0 = registry().counter("cachedop_step_failures",
                            kind="FaultInjected").value

    def loss_fn(x, y):
        fault.check("step.custom")
        return _lossf(net(x), y).mean()

    step = tr.capture(loss_fn)
    fault.inject("step.custom", at=[1])
    x, y = _data()[0]
    with pytest.raises(fault.FaultInjected):
        step(x, y)
    fault.clear()
    assert registry().counter("cachedop_step_failures",
                              kind="FaultInjected").value == c0 + 1


def test_states_bytes_roundtrip():
    net, tr = _build()
    s = _step(net, tr)
    for batch in _data(3):
        s(batch)
    blob = tr.states_bytes()
    assert isinstance(blob, bytes) and blob
    net2, tr2 = _build(seed=5)
    for batch in _data(3, seed=9):
        _step(net2, tr2)(batch)
    tr2.load_states_bytes(blob)
    assert tr2._optimizer.num_update == tr._optimizer.num_update
    a = sorted(float(np.asarray(v._data).sum()) for st in
               tr._updater.states.values() for v in st if v is not None)
    b = sorted(float(np.asarray(v._data).sum()) for st in
               tr2._updater.states.values() for v in st if v is not None)
    assert np.allclose(a, b)


def test_one_shot_iterator_exhaustion_is_not_a_fault(tmp_path):
    """A bare iterator running dry ends the run with outcome
    'data_exhausted' — no budget burned, no recovery attempted."""
    net, tr = _build()
    data = _data(3)
    rep, sup = fault.run_supervised(
        tr, _step(net, tr), iter(data), 10,
        checkpoint_dir=str(tmp_path / "ck"), emergency_save=False)
    assert rep["outcome"] == "data_exhausted"
    assert rep["applied"] == 3
    assert rep["incidents"] == []
    assert rep["budget_remaining"] == sup.restart_budget


def test_rollback_with_unreplayable_source_crashes_structured(tmp_path):
    """Rollback over a bare iterator is a recovery dead end — it must
    exit through the RecoveryExhausted/crash-report contract, not leak
    a bare MXNetError out of run()."""
    net, tr = _build()
    data = _data(30)
    fault.inject("grad.nan", at=[4])
    with pytest.raises(fault.RecoveryExhausted) as ei:
        fault.run_supervised(
            tr, _step(net, tr), iter(data), 20,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
            backoff_base=0.0, emergency_save=False,
            crash_dir=str(tmp_path / "crash"))
    fault.clear()
    assert "rollback impossible" in ei.value.report["reason"]
    assert ei.value.report_path and os.path.exists(ei.value.report_path)


def test_unknown_classify_domain_falls_back_to_transient(tmp_path):
    net, tr = _build()
    fault.inject("grad.nan", at=[3])
    rep, _ = fault.run_supervised(
        tr, _step(net, tr), lambda: iter(_data()), 6,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        backoff_base=0.0, emergency_save=False,
        classify=lambda e: "network")     # off-table domain
    fault.clear()
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["transient"] >= 1


def test_custom_classified_preemption_exits_resumable(tmp_path):
    """A classify hook mapping a cluster's own preemption notice to
    'preemption' gets the domain's promised policy — emergency save +
    resumable exit — not rollback-and-continue on a dying node."""
    class NodeReclaim(RuntimeError):
        pass

    data = _data()
    net, tr = _build()
    calls = {"n": 0}
    real = _step(net, tr)

    def step(batch):
        calls["n"] += 1
        if calls["n"] == 5:
            raise NodeReclaim("node reclaim notice")
        return real(batch)

    cls = lambda e: ("preemption" if isinstance(e, NodeReclaim)  # noqa: E731
                     else fault.classify_failure(e))
    rep, sup = fault.run_supervised(
        tr, step, lambda: iter(data), 10,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=100,
        backoff_base=0.0, emergency_save=False, classify=cls)
    assert rep["outcome"] == "preempted"
    assert rep["applied"] == 4
    assert rep["recoveries"]["preemption"] == 1
    assert rep["budget_remaining"] == sup.restart_budget   # no budget
    # the exit left a resumable checkpoint at the preempted step
    net2, tr2 = _build(seed=50)
    rep2, _ = fault.run_supervised(
        tr2, _step(net2, tr2), lambda: iter(data), 10,
        checkpoint_dir=str(tmp_path / "ck"), emergency_save=False)
    assert rep2["resumed_from"] == 4 and rep2["applied"] == 10


def test_resume_false_over_foreign_steps_refuses(tmp_path):
    """resume=False over a dir holding another run's steps must refuse
    loudly — a later rollback would splice the foreign state in."""
    data = _data()
    net, tr = _build()
    fault.run_supervised(tr, _step(net, tr), lambda: iter(data), 4,
                         checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every=2, emergency_save=False)
    net2, tr2 = _build(seed=8)
    with pytest.raises(mx.base.MXNetError, match="resume=True"):
        fault.run_supervised(tr2, _step(net2, tr2), lambda: iter(data), 4,
                             checkpoint_dir=str(tmp_path / "ck"),
                             resume=False, emergency_save=False)


# ------------------------------------------- ISSUE 18: fleet + grow-back
def test_classify_host_lost_and_domains():
    assert fault.classify_failure(fault.HostLost(2)) == "host_lost"
    # HostLost subclasses nothing device-ish: it must NOT be shadowed by
    # an earlier capacity_loss match
    assert "capacity_gain" in fault.DOMAINS
    assert "host_lost" in fault.DOMAINS


def test_incidents_method_and_jsonl_trail(tmp_path):
    """Every concluded incident — even in a run that never crashes —
    lands in `incidents()` AND as a JSON line in incidents.jsonl, so a
    healthy run still leaves an on-disk trail."""
    crash = tmp_path / "crash"
    fault.inject("kv.collective", at=[5])
    net, tr = _build()
    rep, sup = fault.run_supervised(
        tr, _step(net, tr), lambda: iter(_data()), 6,
        checkpoint_dir=str(tmp_path / "ck"), backoff_base=0.0,
        emergency_save=False, crash_dir=str(crash))
    assert rep["outcome"] == "completed"
    incs = sup.incidents()
    assert incs and incs is not sup.incidents()      # a COPY
    assert any(i["domain"] == "transient" and i.get("recovered")
               for i in incs)
    trail = crash / "incidents.jsonl"
    assert trail.exists()
    lines = [json.loads(ln) for ln in
             trail.read_text().strip().splitlines()]
    assert any(ln["domain"] == "transient" for ln in lines)
    assert all("applied" in ln and "time" in ln for ln in lines)


def _sharded_build(seed=3):
    net, tr = _build(seed)
    plan = tr.shard(mesh={"dp": 2, "tp": 1})
    _lf = gluon.loss.SoftmaxCrossEntropyLoss()
    cstep = tr.capture(lambda x, y: _lf(net(x), y).mean())
    ids = [d.id for d in plan.mesh.devices.flatten()]
    return net, tr, cstep, ids


def test_regrow_when_capacity_returns(tmp_path):
    """Device lost at step 3 shrinks the mesh; the device is unmasked at
    step 6 (fault.clear); the probe must regrow to the ORIGINAL layout
    and devices, count fault_regrows + a capacity_gain recovery, emit an
    incident, and refill the restart budget."""
    rg0 = registry().counter("fault_regrows").value
    net, tr, cstep, ids = _sharded_build()
    orig_axes = {k: int(v) for k, v in tr.shard_plan.mesh.shape.items()}
    fault.inject("device.lost", at=[3], device=ids[-1])
    count = {"n": 0}

    def step(batch):
        count["n"] += 1
        if count["n"] >= 6 and fault.lost_devices():
            fault.clear("device.lost")
        return cstep(batch[0], batch[1])

    rep, sup = fault.run_supervised(
        tr, step, lambda: iter(_data(n=6)), 14,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        backoff_base=0.0, emergency_save=False,
        regrow_cooldown=1, regrow_hysteresis=2)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["capacity_loss"] >= 1
    assert rep["recoveries"]["capacity_gain"] == 1
    assert registry().counter("fault_regrows").value == rg0 + 1
    assert {k: int(v)
            for k, v in tr.shard_plan.mesh.shape.items()} == orig_axes
    assert [d.id for d in tr.shard_plan.mesh.devices.flatten()] == ids
    gains = [i for i in sup.incidents() if i["domain"] == "capacity_gain"]
    assert gains and gains[0]["recovered"]
    assert gains[0]["axes"] == orig_axes
    # the job is whole again: the shrink's budget debit was refunded
    assert rep["budget_remaining"] == sup.restart_budget


def test_regrow_cooldown_gates_thrash(tmp_path):
    """With a cooldown longer than the run there is NO regrow even
    though capacity returned — the thrash guard holds the shrunk mesh."""
    net, tr, cstep, ids = _sharded_build()
    fault.inject("device.lost", at=[3], device=ids[-1])
    count = {"n": 0}

    def step(batch):
        count["n"] += 1
        if count["n"] >= 6 and fault.lost_devices():
            fault.clear("device.lost")
        return cstep(batch[0], batch[1])

    rep, sup = fault.run_supervised(
        tr, step, lambda: iter(_data(n=6)), 12,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        backoff_base=0.0, emergency_save=False,
        regrow_cooldown=1000, regrow_hysteresis=1)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["capacity_gain"] == 0
    assert dict(tr.shard_plan.mesh.shape).get("dp") == 1   # still shrunk
    assert sup._pre_shrink is not None          # probe stays armed


def test_no_regrow_while_device_still_lost(tmp_path):
    """The lost device never returns: the probe must never fire and the
    run completes on the survivor mesh (the pre-18 behavior exactly)."""
    net, tr, cstep, ids = _sharded_build()
    fault.inject("device.lost", at=[3], device=ids[-1])
    rep, sup = fault.run_supervised(
        tr, lambda b: cstep(b[0], b[1]), lambda: iter(_data(n=6)), 10,
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=4,
        backoff_base=0.0, emergency_save=False,
        regrow_cooldown=0, regrow_hysteresis=1)
    assert rep["outcome"] == "completed"
    assert rep["recoveries"]["capacity_gain"] == 0
    assert dict(tr.shard_plan.mesh.shape).get("dp") == 1
