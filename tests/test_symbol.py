"""Symbol API tests (SURVEY.md §2 #12)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _mlp():
    data = sym.Variable("data")
    w1 = sym.Variable("w1")
    b1 = sym.Variable("b1")
    h = sym.Activation(sym.FullyConnected(data, w1, b1, num_hidden=8),
                       act_type="relu")
    w2 = sym.Variable("w2")
    b2 = sym.Variable("b2")
    return sym.FullyConnected(h, w2, b2, num_hidden=3)


def test_variable_and_arguments():
    out = _mlp()
    args = out.list_arguments()
    assert args == ["data", "w1", "b1", "w2", "b2"]
    assert len(out.list_outputs()) == 1


def test_infer_shape():
    out = _mlp()
    arg_shapes, out_shapes, _ = out.infer_shape(
        data=(2, 4), w1=(8, 4), b1=(8,), w2=(3, 8), b2=(3,))
    assert out_shapes == [(2, 3)]


def test_executor_forward_backward():
    out = _mlp()
    rng = np.random.RandomState(0)
    args = {"data": nd.array(rng.rand(2, 4)),
            "w1": nd.array(rng.rand(8, 4)), "b1": nd.zeros((8,)),
            "w2": nd.array(rng.rand(3, 8)), "b2": nd.zeros((3,))}
    grads = {k: nd.zeros(v.shape) for k, v in args.items()}
    ex = out.bind(None, args, grads)
    y = ex.forward(is_train=True)
    y0 = y[0] if isinstance(y, (list, tuple)) else y
    assert y0.shape == (2, 3)
    ex.backward(nd.ones((2, 3)))
    assert np.abs(grads["w1"].asnumpy()).sum() > 0
    assert np.abs(grads["data"].asnumpy()).sum() > 0


def test_simple_bind():
    out = _mlp()
    ex = out.simple_bind(data=(2, 4), w1=(8, 4), b1=(8,), w2=(3, 8), b2=(3,))
    y = ex.forward()
    y0 = y[0] if isinstance(y, (list, tuple)) else y
    assert y0.shape == (2, 3)


def test_symbol_arithmetic_and_eval():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b * 2.0) / 2.0
    out = c.eval_with({"a": nd.array([2.0]), "b": nd.array([4.0])})
    np.testing.assert_allclose(out.asnumpy(), [5.0])


def test_tojson_load_roundtrip():
    out = _mlp()
    js = out.tojson()
    loaded = mx.sym.load_json(js)
    assert loaded.list_arguments() == out.list_arguments()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "net-symbol.json")
        out.save(path)
        again = mx.sym.load(path)
        assert again.list_arguments() == out.list_arguments()


def test_get_internals_and_group():
    out = _mlp()
    internals = out.get_internals()
    names = internals.list_outputs()
    assert any("fullyconnected" in n.lower() or "FullyConnected" in n
               for n in names) or len(names) > 3


def test_symbolblock_from_symbol():
    from mxnet_tpu.gluon import SymbolBlock, nn
    data = sym.Variable("data")
    w = sym.Variable("w")
    out = sym.FullyConnected(data, w, None, num_hidden=4, no_bias=True)
    from mxnet_tpu.gluon.parameter import Parameter
    p = Parameter("w", shape=(4, 3))
    p.initialize()
    blk = SymbolBlock(out, [data], params={"w": p})
    y = blk(nd.ones((2, 3)))
    assert y.shape == (2, 4)


def test_hybridblock_symbolic_trace():
    """Calling a HybridBlock on a Symbol yields a Symbol graph."""
    from mxnet_tpu.gluon import nn
    net = nn.Dense(5, in_units=3)
    net.initialize()
    data = sym.Variable("data")
    out = net(data)
    assert hasattr(out, "list_arguments")
    assert "data" in out.list_arguments()


def test_group_infer_shape():
    """Group-headed symbols infer member shapes (module.py binds Groups)."""
    data = sym.Variable("data")
    w1 = sym.Variable("w1")
    b1 = sym.Variable("b1")
    h = sym.FullyConnected(data, w1, b1, num_hidden=8)
    out2 = sym.Activation(h, act_type="relu")
    g = sym.Group([h, out2])
    arg_shapes, out_shapes, _ = g.infer_shape(data=(2, 4))
    assert out_shapes == [(2, 8), (2, 8)]
    assert (8, 4) in arg_shapes and (8,) in arg_shapes
    nested = sym.Group([sym.Group([h]), out2])
    _, out_shapes, _ = nested.infer_shape(data=(2, 4))
    assert out_shapes == [(2, 8), (2, 8)]


def test_indexed_group_output():
    """g[i] (indexed Group output) infers shapes and evaluates."""
    data = sym.Variable("data")
    w1 = sym.Variable("w1")
    b1 = sym.Variable("b1")
    h = sym.FullyConnected(data, w1, b1, num_hidden=8)
    r = sym.Activation(h, act_type="relu")
    g = sym.Group([h, r])
    one = g[1]
    _, out_shapes, _ = one.infer_shape(data=(2, 4))
    assert out_shapes == [(2, 8)]
    vals = {"data": np.zeros((2, 4), np.float32) - 1.0,
            "w1": np.ones((8, 4), np.float32),
            "b1": np.zeros((8,), np.float32)}
    out = one._eval_with_values({k: mx.nd.array(v)._data
                                 for k, v in vals.items()})
    assert np.allclose(np.asarray(out), 0.0)  # relu(-4) == 0


def test_s2d_stem_symbolic_trace():
    """S2DStemConv traces symbolically (F=sym) like the Conv2D it replaces."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import S2DStemConv
    blk = S2DStemConv(16)
    blk.initialize()
    x = nd.random.uniform(shape=(1, 8, 8, 3))
    blk(x)  # materialise deferred weight
    out = blk(sym.Variable("data"))
    assert "data" in out.list_arguments()
    _, out_shapes, _ = out.infer_shape(data=(2, 8, 8, 3))
    assert out_shapes == [(2, 4, 4, 16)]


def test_batchnorm_aux_states():
    """BN moving stats are auxiliary states, not trainable arguments
    (reference: nnvm mutable inputs excluded from gradients)."""
    data = sym.Variable("data")
    net = sym.BatchNorm(sym.FullyConnected(data, num_hidden=4,
                                           name="fc"), name="bn")
    args = net.list_arguments()
    aux = net.list_auxiliary_states()
    assert "bn_moving_mean" in aux and "bn_moving_var" in aux
    assert not any("moving" in a for a in args)
    arg_shapes, _, aux_shapes = net.infer_shape(data=(2, 3))
    assert len(arg_shapes) == len(args)
    assert aux_shapes == [(4,), (4,)]


def test_batchnorm_train_updates_moving_stats():
    """Executor.forward(is_train=True) uses batch stats and writes the
    moving-average update back to aux_dict; inference uses moving stats."""
    rs = np.random.RandomState(0)
    x_np = (rs.randn(64, 4).astype(np.float32) * 3.0 + 7.0)
    data = sym.Variable("data")
    net = sym.BatchNorm(data, name="bn", momentum=0.5)
    ex = net.simple_bind(grad_req="null", data=(64, 4),
                         bn_gamma=(4,), bn_beta=(4,))
    ex.arg_dict["bn_gamma"]._assign_value(mx.nd.ones((4,))._data)
    mm0 = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    out_t = ex.forward(is_train=True, data=mx.nd.array(x_np))[0]
    # training output is batch-normalised: ~zero mean, unit var
    o = out_t.asnumpy()
    assert abs(o.mean()) < 1e-2 and abs(o.var() - 1.0) < 0.1
    mm1 = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(mm0, mm1)  # moving stats moved
    expect = 0.5 * mm0 + 0.5 * x_np.mean(axis=0)
    np.testing.assert_allclose(mm1, expect, rtol=1e-4, atol=1e-4)
    # inference normalises with the (updated) moving stats
    out_i = ex.forward(is_train=False, data=mx.nd.array(x_np))[0].asnumpy()
    assert abs(out_i.mean()) > 0.1  # not batch-normalised to zero


def test_module_excludes_aux_from_optimizer():
    """Module training must not apply optimizer updates to BN moving stats
    (round-2 review finding)."""
    from mxnet_tpu.module import Module
    from mxnet_tpu.io import NDArrayIter
    rs = np.random.RandomState(1)
    x = rs.randn(32, 6).astype(np.float32)
    y = rs.randint(0, 2, (32,)).astype(np.float32)
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    h = sym.BatchNorm(sym.FullyConnected(data, num_hidden=8, name="fc"),
                      name="bn")
    out = sym.SoftmaxOutput(sym.FullyConnected(h, num_hidden=2, name="out"),
                            label, name="softmax")
    mod = Module(out, data_names=["data"], label_names=["softmax_label"])
    it = NDArrayIter(x, y, batch_size=16)
    mod.fit(it, num_epoch=2, optimizer_params={"learning_rate": 0.1})
    arg_params, aux_params = mod.get_params()
    assert "bn_moving_mean" in aux_params
    assert "bn_moving_mean" not in arg_params
    assert not any(n.endswith("moving_mean") or n.endswith("moving_var")
                   for n in mod._param_names)
    # moving stats were updated by forward passes (train mode), not frozen
    assert not np.allclose(aux_params["bn_moving_mean"].asnumpy(), 0.0)


def test_name_manager_scoped_counters():
    """mx.name.NameManager gives deterministic auto-names regardless of
    prior construction; Prefix prepends (reference: python/mxnet/name.py)."""
    d = sym.Variable("d")
    _ = sym.FullyConnected(d, num_hidden=2)   # bump the global counter
    with mx.name.NameManager():
        s = sym.FullyConnected(d, num_hidden=2)
        assert "fullyconnected0_weight" in s.list_arguments()
    with mx.name.Prefix("enc_"):
        s = sym.FullyConnected(d, num_hidden=2)
        assert "enc_fullyconnected0_weight" in s.list_arguments()


def test_attr_scope():
    """mx.AttrScope attaches attrs to symbols created inside the scope and
    they round-trip through tojson (reference: python/mxnet/attribute.py)."""
    with mx.AttrScope(ctx_group="dev1", stage="encoder"):
        a = sym.Variable("a", attr={"grp": "x"})
        with mx.AttrScope(stage="decoder"):
            b = sym.FullyConnected(a, num_hidden=4, name="fcattr")
    assert a.attr("ctx_group") == "dev1" and a.attr("grp") == "x"
    assert b.attr("stage") == "decoder" and b.attr("ctx_group") == "dev1"
    outside = sym.Variable("c")
    assert outside.attr("ctx_group") is None
    loaded = mx.sym.load_json(b.tojson())
    assert loaded.attr("stage") == "decoder"
    assert "num_hidden" in b.list_attr()  # op attrs still visible
    import pytest
    with pytest.raises(mx.base.MXNetError):
        mx.AttrScope(bad=3)  # non-string values rejected


def test_symbolic_dropout_train_vs_inference():
    """Dropout is identity in inference and drops+rescales in training
    (round-2 review finding: the train variant must not be a no-op)."""
    data = sym.Variable("data")
    net = sym.Dropout(data, p=0.5)
    x = np.ones((64, 64), np.float32)
    ex = net.bind(None, {"data": mx.nd.array(x)},
                  {"data": mx.nd.zeros((64, 64))})
    out_inf = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_array_equal(out_inf, x)  # identity
    out_tr = ex.forward(is_train=True)[0].asnumpy()
    zeros = (out_tr == 0).mean()
    assert 0.3 < zeros < 0.7           # ~half dropped
    kept = out_tr[out_tr != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-6)  # inverted scaling
    out_tr2 = ex.forward(is_train=True)[0].asnumpy()
    assert not np.array_equal(out_tr, out_tr2)  # fresh key per step


def test_softmax_output_use_ignore():
    """SoftmaxOutput(use_ignore=True) zeroes gradients at ignore_label
    positions (reference: softmax_output-inl.h). Without it, padded
    positions emit grad=p and silently corrupt training (found by the
    bucketed-LM end-to-end drive)."""
    x = sym.Variable("x")
    y = sym.Variable("y")
    out = sym.SoftmaxOutput(x, y, use_ignore=True, ignore_label=-1)
    xv = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    yv = nd.array(np.array([0, 2, -1, -1], np.float32))
    grads = {"x": nd.zeros((4, 3)), "y": nd.zeros((4,))}
    ex = out.bind(None, {"x": xv, "y": yv}, grads)
    ex.forward(is_train=True)
    ex.backward()
    g = grads["x"].asnumpy()
    assert np.abs(g[:2]).sum() > 0        # real rows got p - onehot
    np.testing.assert_allclose(g[2:], 0.0)  # ignored rows zeroed
    # default (no ignore): padded rows DO get gradients — reference parity
    out2 = sym.SoftmaxOutput(x, y)
    ex2 = out2.bind(None, {"x": xv, "y": yv},
                    {"x": nd.zeros((4, 3)), "y": nd.zeros((4,))})
    ex2.forward(is_train=True)
    ex2.backward()
    assert np.abs(ex2.grad_dict["x"].asnumpy()[2:]).sum() > 0


def test_deconvolution_symbol_and_transpose_layer_trace():
    """sym.Deconvolution matches the nd kernel, and Conv2DTranspose layers
    trace symbolically (export path for decoder/GAN nets)."""
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.ops import nn_ops as K
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 5, 5).astype(np.float32)
    w = rs.randn(3, 4, 3, 3).astype(np.float32)
    out = sym.Deconvolution(sym.Variable("x"), sym.Variable("w"),
                            kernel=3, stride=2, num_filter=4, no_bias=True)
    ex = out.bind(None, {"x": nd.array(x), "w": nd.array(w)})
    got = ex.forward()[0].asnumpy()
    expect = np.asarray(K.deconvolution(x, w, None, 2, 0, 0, None))
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)
    _, out_shapes, _ = out.infer_shape(x=(2, 3, 5, 5))
    assert out_shapes == [got.shape]

    blk = nn.Conv2DTranspose(6, 3, strides=2)
    blk.initialize()
    blk(nd.array(x))
    traced = blk(sym.Variable("data"))
    _, shapes, _ = traced.infer_shape(data=(2, 3, 5, 5))
    assert shapes[0][1] == 6  # channels out


def test_auto_names_deterministic_and_collision_free():
    """Auto-names come from NameManager monotonic counters at creation:
    the same build sequence under a fresh manager yields byte-identical
    tojson(), and long chains never collide (regression for the old
    id()%10000 scheme — VERDICT r2 weak #3)."""
    def build():
        x = sym.Variable("x")
        h = sym.FullyConnected(x, num_hidden=4)
        h = sym.Activation(h, act_type="relu")
        h = sym.FullyConnected(h, num_hidden=3)
        return h + sym.Variable("bias_extra")

    with mx.name.NameManager():
        j1 = build().tojson()
    with mx.name.NameManager():
        j2 = build().tojson()
    assert j1 == j2  # byte-identical across two constructions

    # 5000-node chain: every auto name unique (the old scheme collided
    # with high probability past ~120 nodes)
    s = sym.Variable("x")
    for _ in range(5000):
        s = sym.Activation(s, act_type="relu")
    names = [n.name for n in s._topo()]
    assert len(names) == len(set(names))


def test_auto_names_assigned_at_creation_order():
    """Names track construction order, not first-access order."""
    with mx.name.NameManager():
        x = sym.Variable("x")
        a = sym.Activation(x, act_type="relu")
        b = sym.Activation(x, act_type="tanh")
        # access b's name first: must still be activation1 (creation order)
        assert b.name == "activation1"
        assert a.name == "activation0"


def test_softmax_use_length_json_roundtrip():
    """Length-masked softmax (reference: softmax(use_length=True)) is a
    2-input node that must survive tojson -> load_json -> bind with the
    mask still biting."""
    d = mx.sym.Variable("scores")
    ln = mx.sym.Variable("ln")
    out = mx.sym.softmax(d, length=ln, axis=-1)
    loaded = mx.sym.load_json(out.tojson())
    scores = mx.nd.random.uniform(shape=(2, 3, 5))
    lens = mx.nd.array(np.array([5, 2], np.float32))
    got = loaded.bind(None, {"scores": scores, "ln": lens}).forward()[0]
    a = got.asnumpy()
    assert np.allclose(a.sum(-1), 1.0, atol=1e-5)
    assert np.allclose(a[1, :, 2:], 0.0, atol=1e-6)
    ref = mx.nd.softmax(scores, length=lens).asnumpy()
    assert np.allclose(a, ref, atol=1e-6)


def test_load_json_malformed_raises_cleanly():
    """Corrupt symbol JSON raises MXNetError at LOAD time for every
    failure class — non-JSON, foreign structure, truncation, and unknown
    op names (validated up front like the reference's nnvm loader, not
    deferred to the first bind)."""
    g = mx.sym.FullyConnected(mx.sym.Variable("d"), num_hidden=4,
                              name="fc")
    js = g.tojson()
    for bad in ("{{{", '{"hello": 1}', js[: len(js) // 2],
                js.replace("FullyConnected", "NoSuchOp")):
        with pytest.raises(mx.base.MXNetError):
            mx.sym.load_json(bad)
    assert mx.sym.load_json(js).list_arguments() == \
        ["d", "fc_weight", "fc_bias"]
