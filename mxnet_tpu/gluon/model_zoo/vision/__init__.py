"""mx.gluon.model_zoo.vision (reference: gluon/model_zoo/vision/__init__.py).

Every constructor here accepts ``pretrained=True`` (+ optional ``root=``):
weights come from the local model store (gluon/model_zoo/model_store.py —
upstream binary .params or native .npz), matching the reference's
download-then-load flow minus the download.
"""
import functools

from .resnet import *        # noqa: F401,F403
from .alexnet import *       # noqa: F401,F403
from .vgg import *           # noqa: F401,F403
from .squeezenet import *    # noqa: F401,F403
from .densenet import *      # noqa: F401,F403
from .mobilenet import *     # noqa: F401,F403
from .inception import *     # noqa: F401,F403
from ..model_store import apply_pretrained

_models = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1, "resnet18_v2": resnet18_v2,
    "resnet34_v2": resnet34_v2, "resnet50_v2": resnet50_v2,
    "resnet101_v2": resnet101_v2, "resnet152_v2": resnet152_v2,
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3,
}


def _with_pretrained(name, builder):
    """Make `pretrained=True` real for every zoo constructor: the raw
    builders either raised or (worse) silently ignored it. Signature
    matches the reference ctors — pretrained/ctx positional-friendly."""
    @functools.wraps(builder)
    def ctor(pretrained=False, ctx=None, root=None, **kwargs):
        net = builder(**kwargs)
        if pretrained:
            apply_pretrained(net, name, root=root, ctx=ctx)
        elif ctx is not None:
            net.collect_params().reset_ctx(ctx)
        return net
    return ctor


_models = {name: _with_pretrained(name, b) for name, b in _models.items()}
# rebind the module-level constructor names so direct calls
# (vision.resnet18_v1(pretrained=True)) go through the store too
for _n, _b in _models.items():
    globals()[_b.__name__] = _b
del _n, _b


# detection constructors (gluoncv get_model names) resolve lazily —
# the models package imports heavier pieces than the classification zoo
_DETECTION = {
    "yolo3_darknet53": ("mxnet_tpu.models.yolo", "yolo3_darknet53"),
    "yolo3_darknet53_voc": ("mxnet_tpu.models.yolo", "yolo3_darknet53"),
    "yolo3_darknet53_coco": ("mxnet_tpu.models.yolo", "yolo3_darknet53"),
    "ssd_512_resnet50_v1": ("mxnet_tpu.models.ssd", "ssd_512_resnet50_v1"),
    "ssd_512_resnet50_v1_voc": ("mxnet_tpu.models.ssd",
                                "ssd_512_resnet50_v1"),
}


def get_model(name, **kwargs):
    """Create a model by name (reference: model_zoo.vision.get_model,
    plus the gluoncv detection names)."""
    name = name.lower()
    if name in _DETECTION:
        import importlib
        mod, fn = _DETECTION[name]
        if kwargs.pop("pretrained", False):
            raise ValueError(
                f"{name}: no pretrained detection weights ship in this "
                "offline environment — train from scratch or load your "
                "own via load_parameters")
        # gluoncv get_model signature compatibility: ctx/root are
        # accepted everywhere; placement is XLA's job here
        ctx = kwargs.pop("ctx", None)
        kwargs.pop("root", None)
        if name.endswith("_coco"):
            kwargs.setdefault("num_classes", 80)
        net = getattr(importlib.import_module(mod), fn)(**kwargs)
        if ctx is not None:
            net.collect_params().reset_ctx(ctx)
        return net
    if name not in _models:
        raise ValueError(
            f"model {name!r} not in zoo: "
            f"{sorted(_models) + sorted(_DETECTION)}")
    return _models[name](**kwargs)
