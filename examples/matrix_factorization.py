"""Matrix-factorization recommender (reference: example/recommenders /
example/sparse/matrix_factorization.py).

The reference trains sparse user/item embeddings; TPU storage is dense
(SURVEY §8), so the embeddings are dense `take`s that XLA turns into MXU
gathers — the model, loss, and training loop are otherwise the
reference's: rating ~ <user_vec, item_vec> + biases, L2 loss.

Usage: python examples/matrix_factorization.py [--epochs N] [--smoke]
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn, Trainer, loss as gloss
from mxnet_tpu.gluon.block import HybridBlock


class MFBlock(HybridBlock):
    def __init__(self, n_users, n_items, k=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = nn.Embedding(n_users, k)
            self.item = nn.Embedding(n_items, k)
            self.user_bias = nn.Embedding(n_users, 1)
            self.item_bias = nn.Embedding(n_items, 1)

    def hybrid_forward(self, F, users, items):
        p = (self.user(users) * self.item(items)).sum(axis=-1)
        return (p + self.user_bias(users).reshape((-1,))
                + self.item_bias(items).reshape((-1,)))


def synthetic_ratings(n_users, n_items, k, n_obs, rng):
    """Ground-truth low-rank ratings + noise."""
    u = rng.randn(n_users, k).astype(onp.float32) / onp.sqrt(k)
    v = rng.randn(n_items, k).astype(onp.float32) / onp.sqrt(k)
    users = rng.randint(0, n_users, n_obs)
    items = rng.randint(0, n_items, n_obs)
    ratings = (u[users] * v[items]).sum(-1) + \
        0.05 * rng.randn(n_obs).astype(onp.float32)
    return users, items, ratings.astype(onp.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    n_users, n_items, k = 200, 150, 8
    epochs = 2 if args.smoke else args.epochs
    n_obs = 512 if args.smoke else 8192

    rng = onp.random.RandomState(0)
    users, items, ratings = synthetic_ratings(n_users, n_items, k,
                                              n_obs, rng)
    net = MFBlock(n_users, n_items, k=k)
    net.initialize(mx.init.Normal(0.05))
    net.hybridize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 0.02, "wd": 1e-5})
    l2 = gloss.L2Loss()
    B = args.batch_size
    for epoch in range(epochs):
        perm = rng.permutation(n_obs)
        total = 0.0
        for lo in range(0, n_obs - B + 1, B):
            sel = perm[lo:lo + B]
            ub = nd.array(users[sel], dtype="int32")
            ib = nd.array(items[sel], dtype="int32")
            rb = nd.array(ratings[sel])
            with mx.autograd.record():
                # Gluon contract: backward the PER-SAMPLE loss vector and
                # let step(batch_size) normalize — adding .mean() here
                # would shrink data-grads by B while weight decay stays
                # full-strength, drowning the signal
                loss = l2(net(ub, ib), rb)
            loss.backward()
            trainer.step(B)
            total += float(loss.mean().asnumpy())
        rmse = (2 * total / max(n_obs // B, 1)) ** 0.5
        print(f"epoch {epoch}: train RMSE ~ {rmse:.4f}")
    if not args.smoke:
        assert rmse < 0.2, rmse
    print("matrix factorization done")


if __name__ == "__main__":
    main()
