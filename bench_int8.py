"""INT8 inference A/B: quantize_net'd ResNet-50 vs the bf16 original.

The reference's quantization story is an INFERENCE-speed story
(contrib.quantization + calibration -> int8 conv/FC kernels). This
bench proves (or honestly refutes) the same claim on TPU: zoo
resnet50_v1 at batch 128, bf16 forward vs the calibrated int8 forward
(MXU int8xint8->int32 dots), hybridized, images/sec each, plus the
ratio. No baseline denominator — the deliverable is the measured
speedup itself, reported in the JSON line.

ISSUE 14: the speed ratio never ships without an accuracy number —
`logit_mse` (mean squared logit error vs the fp forward on a held
batch) and `greedy_match` (top-1 / greedy-prediction agreement rate)
ride the same JSON line, the quality-column contract the serving
low-precision path also follows (bench_serve --int8-kv).

Off by default; BENCH_INT8=1 adds it to bench.py's extra_metrics.
Standalone: `python bench_int8.py` prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time


def measure(on_result=None):
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1, resnet18_v1

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        ctor, mname, batch, size, steps = resnet50_v1, "resnet50", 128, 224, 20
    else:  # CPU smoke uses a smaller model — the metric name says which
        ctor, mname, batch, size, steps = resnet18_v1, "resnet18", 2, 64, 2

    net = ctor(layout="NHWC")
    net.initialize(mx.init.Xavier())
    if on_tpu:
        net.cast("bfloat16")
    dtype = "bfloat16" if on_tpu else "float32"
    x = nd.random.uniform(shape=(batch, size, size, 3), dtype=dtype)
    net(x)  # materialise

    def run(fn, n):
        fn(x)  # warmup/compile
        float(fn(x).asnumpy().sum())  # host-fetch sync
        t0 = time.monotonic()
        for _ in range(n):
            out = fn(x)
        float(out.asnumpy().sum())
        return batch * n / (time.monotonic() - t0)

    net.hybridize()
    fp_s = run(net, steps)
    print(f"[bench_int8] {dtype}: {fp_s:.1f} img/s", file=sys.stderr)

    qnet = quantize_net(net, quantized_dtype="int8",
                        calib_data=[x], calib_mode="naive")
    int8_s = run(qnet, steps)
    print(f"[bench_int8] int8: {int8_s:.1f} img/s "
          f"({int8_s / fp_s:.2f}x)", file=sys.stderr)

    # quality columns (ISSUE 14): logit MSE + greedy-prediction match on
    # a held batch, so the ratio above never ships alone
    ref_logits = np.asarray(net(x).asnumpy(), np.float64)
    q_logits = np.asarray(qnet(x).asnumpy(), np.float64)
    logit_mse = float(np.mean((ref_logits - q_logits) ** 2))
    greedy_match = float(np.mean(
        ref_logits.argmax(axis=-1) == q_logits.argmax(axis=-1)))
    print(f"[bench_int8] logit MSE {logit_mse:.3e}, greedy match "
          f"{greedy_match:.4f}", file=sys.stderr)

    res = {
        "metric": f"{mname}_int8_inference_throughput",
        "value": round(int8_s, 1),
        "unit": "images/sec/chip",
        # NOT vs_baseline: every other bench reserves that key for the
        # external A100-class denominator; this bench's deliverable is
        # the speedup over the SAME chip's fp path
        "speedup_vs_fp": round(int8_s / fp_s, 4),
        "fp_samples_s": round(fp_s, 1),
        "logit_mse": logit_mse,
        "greedy_match": round(greedy_match, 4),
    }
    if on_result is not None:
        on_result(res)
    return res


def main():
    # honor JAX_PLATFORMS=cpu despite the axon sitecustomize (same dance
    # as bench.py — jax.config wins if set before backend init)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(measure()))


if __name__ == "__main__":
    main()
