"""Multi-host (multi-process) bootstrap smoke tests (VERDICT r1 #4 /
SURVEY §1 distributed row; reference: kvstore_dist ps-lite bootstrap).

Spawns REAL separate processes that rendezvous through
`kvstore.init_distributed` (jax.distributed.initialize) on the CPU
backend, then run a cross-process psum over the global device mesh — the
same code path a TPU pod uses over DCN.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
from mxnet_tpu import kvstore

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
kvstore.init_distributed(f"localhost:{{port}}", nproc, pid)
kv = kvstore.create("ici")
assert kv.num_workers == nproc, kv.num_workers
assert kv.rank == pid, kv.rank

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import make_array_from_process_local_data
from mxnet_tpu.jax_compat import shard_map

mesh = Mesh(jax.devices(), ("dp",))
f = shard_map(lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
              in_specs=P("dp"), out_specs=P())
local = np.full((1, 4), float(pid + 1), np.float32)
g = make_array_from_process_local_data(NamedSharding(mesh, P("dp")), local)
try:
    got = np.asarray(jax.device_get(f(g)))
except Exception as e:  # jaxlib 0.4.x CPU backend: no multiprocess psum
    if "Multiprocess computations aren't implemented" in str(e):
        print("SKIP multiprocess-cpu-unsupported", flush=True)
        sys.exit(0)
    raise
expect = nproc * (nproc + 1) / 2.0
assert np.allclose(got, expect), got
print(f"OK rank={{pid}} workers={{nproc}} psum={{got[0][0]}}", flush=True)
'''


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("nproc", [2])
def test_multiprocess_init_and_psum(tmp_path, nproc):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER.format(repo=repo))
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(nproc), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out in outs:
        assert rc == 0, out
    if any("SKIP multiprocess-cpu-unsupported" in out for _, out in outs):
        # rendezvous + rank/num_workers asserts DID run in every worker;
        # only the cross-process psum is beyond this jaxlib's CPU backend
        pytest.skip("installed jaxlib cannot run multiprocess CPU psum")
    for rc, out in outs:
        assert "OK rank=" in out, out


def test_import_does_not_initialize_backend():
    """`import mxnet_tpu` must stay backend-free — otherwise
    jax.distributed.initialize after import is impossible (and importing
    the library would grab the TPU)."""
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import mxnet_tpu\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb.backends_are_initialized(), 'import touched backend'\n"
        "print('clean')\n")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120, env=env,
                         cwd=repo)
    assert out.returncode == 0 and "clean" in out.stdout, \
        out.stdout + out.stderr
