"""Gluon losses (reference: python/mxnet/gluon/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import _apply, _lift
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CTCLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss", "GaussianNLLLoss", "SDMLLoss"]


def _reduce(x, weight, sample_weight, batch_axis):
    if sample_weight is not None:
        x = x * sample_weight
    if weight is not None:
        x = x * weight
    axes = tuple(i for i in range(x.ndim) if i != batch_axis)
    return jnp.mean(x, axis=axes) if axes else x


class Loss(HybridBlock):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis}, w={self._weight})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw):
            x = jnp.square(l.reshape(p.shape) - p) / 2
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class L1Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw):
            x = jnp.abs(l.reshape(p.shape) - p)
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        ins = [pred, _lift(label)]
        has_sw = sample_weight is not None
        has_pw = pos_weight is not None
        if has_sw:
            ins.append(sample_weight)
        if has_pw:
            ins.append(_lift(pos_weight))

        def fn(p, l, *rest, _fs=self._from_sigmoid, _sw=has_sw, _pw=has_pw):
            sw = rest[0] if _sw else None
            pw = rest[-1] if _pw else None
            l = l.reshape(p.shape)
            if not _fs:
                if pw is None:
                    # log-sum-exp stable BCE with logits
                    x = jax.nn.relu(p) - p * l \
                        + jnp.log1p(jnp.exp(-jnp.abs(p)))
                else:
                    # positive term scaled by pos_weight; stable via softplus
                    logsig = -jax.nn.softplus(-p)       # log sigmoid(p)
                    log1msig = -p - jax.nn.softplus(-p)  # log(1-sigmoid(p))
                    x = -(pw * l * logsig + (1 - l) * log1msig)
            else:
                if pw is None:
                    x = -(l * jnp.log(p + 1e-12)
                          + (1 - l) * jnp.log(1 - p + 1e-12))
                else:
                    x = -(pw * l * jnp.log(p + 1e-12)
                          + (1 - l) * jnp.log(1 - p + 1e-12))
            return _reduce(x, self._weight, sw, self._batch_axis)
        return _apply(fn, ins)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax CE (reference semantics: sparse labels by default)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _ax=self._axis, _sp=self._sparse_label,
               _fl=self._from_logits):
            logp = p if _fl else jax.nn.log_softmax(p, axis=_ax)
            if _sp:
                li = l.astype(jnp.int32)
                x = -jnp.take_along_axis(logp, jnp.expand_dims(li, _ax),
                                         axis=_ax)
                x = jnp.squeeze(x, _ax)
            else:
                x = -jnp.sum(logp * l, axis=_ax)
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _ax=self._axis, _fl=self._from_logits):
            logp = p if _fl else jax.nn.log_softmax(p, axis=_ax)
            x = l * (jnp.log(l + 1e-12) - logp)
            return _reduce(jnp.mean(x, axis=_ax), self._weight,
                           sw[0] if sw else None, self._batch_axis)
        return _apply(fn, ins)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _r=self._rho):
            d = jnp.abs(l.reshape(p.shape) - p)
            x = jnp.where(d > _r, d - 0.5 * _r, 0.5 / _r * jnp.square(d))
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class HingeLoss(Loss):
    def __init__(self, margin=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _m=self._margin):
            x = jax.nn.relu(_m - p * l.reshape(p.shape))
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class SquaredHingeLoss(HingeLoss):
    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _m=self._margin):
            x = jnp.square(jax.nn.relu(_m - p * l.reshape(p.shape)))
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        ins = [pred, _lift(label)] + ([sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw, _lf=self._label_format):
            l = l.reshape(p.shape)
            if _lf == "signed":
                l = (l + 1) / 2
            x = jax.nn.relu(p) - p * l + jnp.log1p(jnp.exp(-jnp.abs(p)))
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class TripletLoss(Loss):
    def __init__(self, margin=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        ins = [pred, _lift(positive), _lift(negative)]

        def fn(a, p, n, _m=self._margin):
            axes = tuple(range(1, a.ndim))
            x = jax.nn.relu(jnp.sum(jnp.square(a - p) - jnp.square(a - n),
                                    axis=axes) + _m)
            return x
        return _apply(fn, ins)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        ins = [input1, _lift(input2), _lift(label)]

        def fn(a, b, l, _m=self._margin):
            cos = jnp.sum(a * b, -1) / (
                jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
                + 1e-12)
            l = l.reshape(cos.shape)
            return jnp.where(l > 0, 1 - cos, jax.nn.relu(cos - _m))
        return _apply(fn, ins)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference: gluon/loss.py
    SDMLLoss): for paired batches (x1[i] matches x2[i]), cross-entropy
    between label-smoothed identity targets and the softmax over
    NEGATIVE pairwise euclidean distances — relative distances learn a
    retrieval metric without explicit negative mining."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing = float(smoothing_parameter)

    def hybrid_forward(self, F, x1, x2, sample_weight=None):
        ins = [x1, _lift(x2)] + ([sample_weight]
                                 if sample_weight is not None else [])

        def fn(a, b, *sw, _sm=self._smoothing):
            n = a.shape[0]
            if n < 2:
                raise MXNetError(
                    "SDMLLoss needs batch >= 2 (the loss contrasts each "
                    "pair against the rest of the batch; drop the last "
                    "partial batch or use last_batch_handle='discard')")
            d = jnp.sqrt(jnp.sum((a[:, None, :] - b[None, :, :]) ** 2,
                                 -1) + 1e-12)
            logp = jax.nn.log_softmax(-d, axis=-1)
            # label smoothing over the off-diagonal
            target = (jnp.eye(n) * (1.0 - _sm)
                      + (1.0 - jnp.eye(n)) * _sm / (n - 1))
            x = -jnp.sum(target * logp, axis=-1)
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)


class CTCLoss(Loss):
    """Connectionist temporal classification (reference: CTCLoss).

    Dynamic-programming forward computed with lax.scan over time — fully
    XLA-compilable, blank label = 0 or alphabet_size-1 per layout."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        ins = [pred, _lift(label)]
        has_pl = pred_lengths is not None
        has_ll = label_lengths is not None
        if has_pl:
            ins.append(_lift(pred_lengths))
        if has_ll:
            ins.append(_lift(label_lengths))

        def fn(p, l, *rest, _layout=self._layout, _pl=has_pl, _ll=has_ll):
            plen = rest[0] if _pl else None
            llen = rest[-1] if _ll else None
            if _layout == "TNC":
                p = jnp.swapaxes(p, 0, 1)
            logp = jax.nn.log_softmax(p, axis=-1)   # (N, T, C); blank=0
            n, t, c = logp.shape
            l = l.astype(jnp.int32)                  # (N, L)
            L = l.shape[1]
            plen = plen.astype(jnp.int32) if plen is not None \
                else jnp.full((n,), t, jnp.int32)
            llen = llen.astype(jnp.int32) if llen is not None \
                else jnp.full((n,), L, jnp.int32)
            # extended labels with interleaved blanks: length 2L+1
            ext = jnp.zeros((n, 2 * L + 1), jnp.int32)
            ext = ext.at[:, 1::2].set(l)
            neg_inf = -1e30
            alpha0 = jnp.full((n, 2 * L + 1), neg_inf)
            alpha0 = alpha0.at[:, 0].set(logp[:, 0, 0])
            alpha0 = alpha0.at[:, 1].set(
                jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0])

            same = jnp.concatenate(
                [jnp.ones((n, 2), bool),
                 ext[:, 2:] == ext[:, :-2]], axis=1)

            def step(alpha, inp):
                lp_t, t_idx = inp
                shifted1 = jnp.concatenate(
                    [jnp.full((n, 1), neg_inf), alpha[:, :-1]], axis=1)
                shifted2 = jnp.concatenate(
                    [jnp.full((n, 2), neg_inf), alpha[:, :-2]], axis=1)
                shifted2 = jnp.where(same, neg_inf, shifted2)
                merged = jnp.logaddexp(jnp.logaddexp(alpha, shifted1), shifted2)
                emit = jnp.take_along_axis(lp_t, ext, axis=1)
                new = merged + emit
                # sequences already past their pred_length keep alpha frozen
                active = (t_idx < plen)[:, None]
                return jnp.where(active, new, alpha), None

            alpha_T, _ = jax.lax.scan(
                step, alpha0,
                (jnp.swapaxes(logp, 0, 1)[1:], jnp.arange(1, t)))
            # final positions depend on each sequence's label length:
            # ext indices 2*llen (trailing blank) and 2*llen - 1 (last label)
            idx_blank = (2 * llen)[:, None]
            idx_label = jnp.maximum(2 * llen - 1, 0)[:, None]
            a_blank = jnp.take_along_axis(alpha_T, idx_blank, axis=1)[:, 0]
            a_label = jnp.take_along_axis(alpha_T, idx_label, axis=1)[:, 0]
            ll_ = jnp.logaddexp(a_blank, a_label)
            return -ll_
        return _apply(fn, ins)


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood (reference: gluon/loss.py
    PoissonNLLLoss): pred is the rate (or its log with from_logits),
    L = pred - label*log(pred) [+ Stirling approx of log(label!)]."""

    def __init__(self, weight=1.0, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       epsilon=1e-8):
        ins = [pred, _lift(label)] + (
            [sample_weight] if sample_weight is not None else [])

        def fn(p, l, *sw):
            l = l.reshape(p.shape)
            if self._from_logits:
                x = jnp.exp(p) - l * p
            else:
                x = p - l * jnp.log(p + epsilon)
            if self._compute_full:
                # Stirling: label*log(label) - label + 0.5*log(2*pi*label),
                # applied where label > 1 (the reference's guard)
                stirling = (l * jnp.log(jnp.maximum(l, 1.0)) - l
                            + 0.5 * jnp.log(2 * jnp.pi * jnp.maximum(l, 1.0)))
                x = x + jnp.where(l > 1.0, stirling, 0.0)
            # reference reduces to the mean over ALL elements
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis).mean()
        return _apply(fn, ins)


class GaussianNLLLoss(Loss):
    """Heteroscedastic Gaussian NLL: 0.5*(log(var) + (pred-label)^2/var),
    clamped at `eps` (torch-compatible semantics; MXNet 2.x parity)."""

    def __init__(self, weight=1.0, batch_axis=0, eps=1e-6, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._eps = eps

    def hybrid_forward(self, F, pred, label, var, sample_weight=None):
        ins = [pred, _lift(label), _lift(var)] + (
            [sample_weight] if sample_weight is not None else [])

        def fn(p, l, v, *sw):
            v = jnp.maximum(v, self._eps)
            x = 0.5 * (jnp.log(v) + jnp.square(l.reshape(p.shape) - p) / v)
            return _reduce(x, self._weight, sw[0] if sw else None,
                           self._batch_axis)
        return _apply(fn, ins)
