"""Legacy symbolic RNN cell API (reference: python/mxnet/rnn/rnn_cell.py).

The classic bucketing / word-LM examples build their networks from these
cells: construct the cells once (weights are shared across time steps),
call ``unroll(length, inputs)`` inside a BucketingModule's ``sym_gen``,
bind, fit.

TPU-first notes:
- An unrolled cell graph is a static-length chain of FullyConnected +
  elementwise nodes — exactly what XLA fuses well, and each bucket is
  one compiled executable (SURVEY §3), so the per-step Python loop here
  costs nothing at run time.
- ``FusedRNNCell`` emits the single ``sym.RNN`` node, whose executor
  lowers the whole stack to one ``lax.scan`` (gluon/rnn/rnn_layer.py) —
  the TPU counterpart of the cuDNN fused path this cell selects
  upstream. Gate order matches the fused kernel ([i,f,g,o] LSTM,
  [r,z,n] GRU) and ``unfuse()`` produces cells whose parameter names
  coincide with the fused ``pnames``, so the same checkpoint binds both
  ways.
- ``begin_state`` divergence: upstream passes ``shape=(0, H)`` and lets
  nnvm back-infer the 0 batch dim. Our executor traces concrete shapes,
  so zero states are graph nodes derived from a `like` tensor (unroll
  wires this automatically) or built eagerly from an explicit
  ``batch_size``.
- Upstream attaches an ``__init__`` attr so LSTM forget biases start at
  ``forget_bias``; here pass ``mx.init.LSTMBias(forget_bias)`` (or a
  ``Mixed`` pattern on ``*_i2h_bias``) to Module init — the cell keeps
  the argument for API parity and records it on the bias variable's
  user attrs.
"""
from __future__ import annotations

from ..base import MXNetError
from ..symbol import ops as S
from ..symbol.symbol import Symbol, Variable, _make

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container for cell weights (reference: RNNParams). ``get`` returns
    the same Variable for the same name, so cells called at every
    timestep share one weight set."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = Variable(name, **kwargs)
        return self._params[name]


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """list-of-(N,C) <-> merged (N,T,C)/(T,N,C) normalisation (reference:
    rnn_cell._normalize_sequence). Returns (inputs, axis) where axis is
    the time axis of the ORIGINAL layout."""
    if layout not in ("NTC", "TNC"):
        raise MXNetError(f"unsupported layout {layout!r} (NTC or TNC)")
    axis = layout.find("T")
    if isinstance(inputs, Symbol):
        if merge is False:
            sliced = S.SliceChannel(inputs, num_outputs=length, axis=axis,
                                    squeeze_axis=True)
            inputs = [sliced[i] for i in range(length)]
    else:
        inputs = list(inputs)
        if length is not None and len(inputs) != length:
            raise MXNetError(f"expected {length} inputs, got {len(inputs)}")
        if merge is True:
            inputs = [S.expand_dims(i, axis=axis) for i in inputs]
            inputs = S.concat(*inputs, dim=axis)
    return inputs, axis


class BaseRNNCell:
    """Abstract cell (reference: BaseRNNCell). Subclasses implement
    ``__call__(inputs, states) -> (output, next_states)`` and
    ``state_info``."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        """Before re-unrolling: restart the per-timestep name counter."""
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, like=None, batch_size=0,
                    batch_axis=0, **kwargs):
        """Initial states. With ``like`` (any Symbol whose ``batch_axis``
        axis is the batch), states are graph-derived zeros — what
        ``unroll`` passes. With ``batch_size``, concrete zeros. With
        ``func``, upstream-style ``func(name=..., shape=..., **kwargs)``."""
        if self._modified:
            raise MXNetError(
                "begin_state on a modifier-wrapped cell: call it on the "
                "wrapper (ZoneoutCell/ResidualCell own the state)")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            shape = tuple(info["shape"])
            if func is not None:
                if 0 in shape and batch_size:
                    shape = tuple(batch_size if s == 0 else s
                                  for s in shape)
                elif 0 in shape:
                    # upstream's func=sym.zeros with shape=(0, H) relies
                    # on nnvm back-inferring the 0 batch dim; here a
                    # 0-dim would silently build EMPTY state arrays
                    raise MXNetError(
                        "begin_state(func=...) needs batch_size= (the "
                        "0-batch back-inference is an nnvm feature; "
                        "XLA shapes are concrete)")
                states.append(func(name=name, shape=shape, **kwargs))
            elif like is not None:
                states.append(_make("_rnn_zero_state", [like],
                                    {"shape": shape,
                                     "batch_axis": batch_axis},
                                    name=name))
            elif batch_size:
                states.append(S.zeros(
                    shape=tuple(batch_size if s == 0 else s for s in shape),
                    name=name))
            else:
                raise MXNetError(
                    "begin_state needs `like=` (a Symbol carrying the "
                    "batch dim), `batch_size=`, or an explicit `func` — "
                    "shapes are concrete under XLA tracing")
        return states

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll ``length`` steps (reference: BaseRNNCell.unroll).
        Returns (outputs, final_states); ``merge_outputs=None`` keeps the
        form of ``inputs`` (merged in -> merged out)."""
        self.reset()
        was_merged = isinstance(inputs, Symbol)
        steps, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(like=steps[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(steps[i], states)
            outputs.append(output)
        if merge_outputs is None:
            merge_outputs = was_merged
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    # fused<->unfused weight conversion is the identity here: the fused
    # sym.RNN node takes the SAME per-matrix parameters the unfused
    # cells use (no cuDNN flat blob on TPU — rnn_layer.py), so a
    # checkpoint binds either form directly.
    def unpack_weights(self, args):
        return dict(args)

    def pack_weights(self, args):
        return dict(args)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell, tanh or relu (reference: rnn_cell.RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = S.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                               num_hidden=self._num_hidden,
                               name=f"{name}i2h")
        h2h = S.FullyConnected(data=states[0], weight=self._hW,
                               bias=self._hB,
                               num_hidden=self._num_hidden,
                               name=f"{name}h2h")
        output = S.Activation(i2h + h2h, act_type=self._activation,
                              name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference: rnn_cell.LSTMCell). Gate order [i, f, g, o]
    — the fused kernel's order, so fused/unfused share checkpoints."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        # record the upstream init contract on the variable for tooling;
        # apply it via mx.init.LSTMBias at Module init time
        self._iB._user_attrs = {
            **getattr(self._iB, "_user_attrs", {}),
            "__init__": f"lstmbias(forget_bias={forget_bias})"}
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        H = self._num_hidden
        return [{"shape": (0, H), "__layout__": "NC"},
                {"shape": (0, H), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        H = self._num_hidden
        i2h = S.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                               num_hidden=4 * H, name=f"{name}i2h")
        h2h = S.FullyConnected(data=states[0], weight=self._hW,
                               bias=self._hB, num_hidden=4 * H,
                               name=f"{name}h2h")
        gates = i2h + h2h
        sliced = S.SliceChannel(gates, num_outputs=4, axis=1,
                                name=f"{name}slice")
        in_gate = S.Activation(sliced[0], act_type="sigmoid")
        forget_gate = S.Activation(sliced[1], act_type="sigmoid")
        in_transform = S.Activation(sliced[2], act_type="tanh")
        out_gate = S.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * S.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference: rnn_cell.GRUCell). Gate order [r, z, n]."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        H = self._num_hidden
        prev = states[0]
        i2h = S.FullyConnected(data=inputs, weight=self._iW, bias=self._iB,
                               num_hidden=3 * H, name=f"{name}i2h")
        h2h = S.FullyConnected(data=prev, weight=self._hW, bias=self._hB,
                               num_hidden=3 * H, name=f"{name}h2h")
        i2h_s = S.SliceChannel(i2h, num_outputs=3, axis=1,
                               name=f"{name}i2h_slice")
        h2h_s = S.SliceChannel(h2h, num_outputs=3, axis=1,
                               name=f"{name}h2h_slice")
        reset = S.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = S.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        next_h_tmp = S.Activation(i2h_s[2] + reset * h2h_s[2],
                                  act_type="tanh")
        ones = _make("_rnn_ones_like", [update], {})
        next_h = (ones - update) * next_h_tmp + update * prev
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-stack fused cell (reference: rnn_cell.FusedRNNCell — the
    cuDNN path). Emits ONE ``sym.RNN`` node; the executor runs the full
    multi-layer (bi)RNN as a single lax.scan program. Only ``unroll``
    works (like upstream: no per-step ``__call__``)."""

    _MODES = ("rnn_relu", "rnn_tanh", "lstm", "gru")

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if mode not in self._MODES:
            raise MXNetError(f"FusedRNNCell mode must be one of "
                             f"{self._MODES}, got {mode!r}")
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix, params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._pnames = []
        in_sfx = ["l"] + (["r"] if bidirectional else [])
        for layer in range(num_layers):
            for sfx in in_sfx:
                for part in ("i2h", "h2h"):
                    self._pnames.append(f"{sfx}{layer}_{part}_weight")
                    self._pnames.append(f"{sfx}{layer}_{part}_bias")
        self._pvars = [self.params.get(n) for n in self._pnames]

    @property
    def _num_dir(self):
        return 2 if self._bidirectional else 1

    @property
    def state_info(self):
        LD = self._num_layers * self._num_dir
        H = self._num_hidden
        info = [{"shape": (LD, 0, H), "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (LD, 0, H), "__layout__": "LNC"})
        return info

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped — call unroll() "
                         "(upstream fused cells are sequence-level too)")

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        was_merged = isinstance(inputs, Symbol)
        # the fused node wants the merged sequence; the batch axis of
        # the merged layout feeds the zero-state (TNC puts it second)
        inputs, _ = _normalize_sequence(length, inputs, layout, True)
        if begin_state is None:
            begin_state = self.begin_state(
                like=inputs, batch_axis=(0 if layout == "NTC" else 1))
        ns = 2 if self._mode == "lstm" else 1
        out = S.RNN(inputs, *begin_state, *self._pvars,
                    mode=self._mode, num_layers=self._num_layers,
                    num_dir=self._num_dir, hidden_size=self._num_hidden,
                    layout_ntc=(layout == "NTC"),
                    pnames=tuple(self._pnames), state_outputs=True,
                    dropout=self._dropout, name=f"{self._prefix}rnn")
        outputs = out[0]
        states = [out[1 + i] for i in range(ns)] \
            if self._get_next_state else []
        if merge_outputs is None:
            merge_outputs = was_merged
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (reference:
        FusedRNNCell.unfuse). Parameter names coincide with the fused
        ``pnames`` (prefix + l{i}_...), so weights bind either way —
        no blob repacking needed on TPU (see pack_weights note)."""
        if self._bidirectional:
            raise MXNetError("unfuse: bidirectional stacks unroll only "
                             "fused (upstream unfuses to BidirectionalCell"
                             " — use FusedRNNCell directly on TPU)")
        # each sub-cell owns RNNParams(prefix + l{i}_): its variable
        # names then equal the fused node's prefix+pname, so the same
        # arg dict binds both graphs (upstream needs unpack_weights for
        # this; TPU-side the names already coincide)
        stack = SequentialRNNCell()
        make = {"rnn_relu":
                lambda p: RNNCell(self._num_hidden, activation="relu",
                                  prefix=p),
                "rnn_tanh":
                lambda p: RNNCell(self._num_hidden, activation="tanh",
                                  prefix=p),
                "lstm":
                lambda p: LSTMCell(self._num_hidden, prefix=p,
                                   forget_bias=self._forget_bias),
                "gru":
                lambda p: GRUCell(self._num_hidden, prefix=p)}[self._mode]
        for layer in range(self._num_layers):
            stack.add(make(f"{self._prefix}l{layer}_"))
            if self._dropout > 0 and layer < self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix=f"{self._prefix}_dropout{layer}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack cells in sequence (reference: SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("begin_state on a modifier-wrapped cell")
        return [s for c in self._cells for s in c.begin_state(**kwargs)]

    def reset(self):
        super().reset()
        for c in getattr(self, "_cells", ()):
            c.reset()

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info)
            cell_states = states[pos:pos + n]
            pos += n
            inputs, new = cell(inputs, cell_states)
            next_states.extend(new)
        return inputs, next_states

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        # per-cell unroll so a FusedRNNCell member could still fuse is
        # upstream behaviour; the simple chain matches it for the
        # unfused cells this container holds
        self.reset()
        was_merged = isinstance(inputs, Symbol)
        steps, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(like=steps[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(steps[i], states)
            outputs.append(output)
        if merge_outputs is None:
            merge_outputs = was_merged
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in opposite directions and concat
    the per-step outputs (reference: BidirectionalCell). Unroll-only."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._l_cell = l_cell
        self._r_cell = r_cell
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return self._l_cell.state_info + self._r_cell.state_info

    def begin_state(self, **kwargs):
        if self._modified:
            raise MXNetError("begin_state on a modifier-wrapped cell")
        return (self._l_cell.begin_state(**kwargs) +
                self._r_cell.begin_state(**kwargs))

    def reset(self):
        super().reset()
        for c in (getattr(self, "_l_cell", None),
                  getattr(self, "_r_cell", None)):
            if c is not None:
                c.reset()

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped — the "
                         "reverse direction needs the whole sequence; "
                         "call unroll()")

    def unroll(self, length, inputs=None, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        was_merged = isinstance(inputs, Symbol)
        steps, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self.begin_state(like=steps[0])
        nl = len(self._l_cell.state_info)
        l_outputs, l_states = self._l_cell.unroll(
            length, steps, begin_state[:nl], layout, merge_outputs=False)
        r_outputs, r_states = self._r_cell.unroll(
            length, list(reversed(steps)), begin_state[nl:], layout,
            merge_outputs=False)
        outputs = [S.concat(lo, ro, dim=1,
                            name=f"{self._output_prefix}t{i}")
                   for i, (lo, ro) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs is None:
            merge_outputs = was_merged
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states


class DropoutCell(BaseRNNCell):
    """Dropout on the per-step output, stateless (reference:
    DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix, params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        self._counter += 1
        if self._dropout > 0:
            inputs = S.Dropout(inputs, p=self._dropout,
                               name=f"{self._prefix}t{self._counter}")
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell (reference: ModifierCell).
    The wrapped cell's params are reused; the wrapper owns none."""

    def __init__(self, base_cell):
        super().__init__(prefix=base_cell._prefix + "mod_", params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def reset(self):
        super().reset()
        if getattr(self, "base_cell", None) is not None:
            self.base_cell.reset()


class ZoneoutCell(ModifierCell):
    """Zoneout regularisation (reference: ZoneoutCell; Krueger et al.):
    with probability z, a state unit keeps its previous value. Uses the
    Dropout node's train/inference split, so inference is the expected
    identity blend."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, FusedRNNCell):
            raise MXNetError("ZoneoutCell needs a steppable cell; "
                             "FusedRNNCell is sequence-level (upstream "
                             "raises here too)")
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states):
        output, next_states = self.base_cell(inputs, states)

        def zone(new, old, rate):
            # Dropout(ones, p=rate)*(1-rate) is 1 w.p. (1-rate): the
            # KEEP-NEW mask (inverted-dropout scaling undone). A unit
            # zones out (keeps old) w.p. rate; inference blends
            # (1-rate)*new + rate*old, the zoneout expectation.
            mask = S.Dropout(_make("_rnn_ones_like", [new], {}),
                             p=rate) * (1.0 - rate)
            return mask * new + (1.0 - mask) * old

        prev = self._prev_output
        if prev is None:
            prev = _make("_rnn_zero_state", [output],
                         {"shape": (0,) + tuple(
                             self.base_cell.state_info[0]["shape"][1:])})
        if self._zoneout_outputs > 0:
            output = zone(output, prev, self._zoneout_outputs)
        self._prev_output = output
        if self._zoneout_states > 0:
            next_states = [zone(n, o, self._zoneout_states)
                           for n, o in zip(next_states, states)]
        return output, next_states


class ResidualCell(ModifierCell):
    """Output = cell(output) + inputs (reference: ResidualCell)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states
