"""bench_util protocol tests: the shared sweep (already covered in
test_bench_supervisor.py) and the shared SGD-momentum step builder the
four bench workers compile."""
import sys
import os
import numpy as np
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench_util import make_sgd_step  # noqa: E402


def _quad_loss(p, x):
    # d(loss)/dp0 = p0 - x  -> SGD converges p0 -> x; p1 is an aux slot
    return 0.5 * jnp.sum((p[0] - x) ** 2), [p[1] + 1.0]


def test_make_sgd_step_momentum_and_aux():
    p = [jnp.zeros(3), jnp.zeros(())]
    mom = [jnp.zeros(3), jnp.zeros(())]
    x = jnp.array([1.0, 2.0, 3.0])
    step = make_sgd_step(_quad_loss, aux_idx=[1], lr=0.1, mu=0.9)
    p1, mom1, loss = step([jnp.array(v) for v in p],
                          [jnp.array(v) for v in mom], x)
    # first step: g = -x, mom = g, p0 = 0.1*x
    np.testing.assert_allclose(np.asarray(p1[0]), 0.1 * np.asarray(x),
                               rtol=1e-6)
    # aux splice: slot 1 got the returned aux value, NOT an SGD update
    assert float(p1[1]) == 1.0
    assert float(loss) == 7.0  # 0.5*(1+4+9)


def test_make_sgd_step_unroll_equals_sequential():
    x = jnp.array([1.0, -2.0])

    def run(unroll, n_dispatch):
        step = make_sgd_step(_quad_loss, aux_idx=[1], lr=0.05, mu=0.9,
                             unroll=unroll)
        p = [jnp.zeros(2), jnp.zeros(())]
        m = [jnp.zeros(2), jnp.zeros(())]
        for _ in range(n_dispatch):
            p, m, loss = step(p, m, x)
        return np.asarray(p[0]), float(p[1]), float(loss)

    p_seq, aux_seq, l_seq = run(1, 6)
    p_unr, aux_unr, l_unr = run(3, 2)
    np.testing.assert_allclose(p_unr, p_seq, rtol=1e-6)
    # aux (BN running stats in the real benches) advances once per REAL
    # step: 6 sequential dispatches == 2 dispatches of 3 unrolled steps
    assert aux_seq == 6.0 and aux_unr == 6.0
    np.testing.assert_allclose(l_unr, l_seq, rtol=1e-6)
