"""mx.contrib.onnx (reference: python/mxnet/contrib/onnx).

Both directions are self-contained (hand-rolled protobuf wire format —
see proto.py); no `onnx` package needed:
  * export_model: Symbol + params → .onnx (mx2onnx)
  * import_model / import_to_gluon: .onnx → Symbol + params (onnx2mx)
"""
from .export import export_model
from .import_model import import_model, import_to_gluon
from . import proto

__all__ = ["export_model", "import_model", "import_to_gluon", "proto"]
