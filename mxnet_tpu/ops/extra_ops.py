"""Classic reference ops outside the core CNN/NLP set (reference:
src/operator/{lrn,l2_normalization,upsampling,bilinear_resize,crop,
slice_channel,roi_pooling,spatial_transformer,correlation,make_loss}.cc
+ tensor ops batch_take/ravel/unravel/digamma).

Every kernel is a static-shape vectorised XLA program (shifts, gathers,
`jax.image.resize`) rather than the reference's per-element CUDA loops, so
they fuse into surrounding jit programs. ROIPooling is provided for parity
but `detection_ops.roi_align` is the production path on TPU (quantised max
bins need data-dependent windows, which XLA only handles via masking).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply

__all__ = ["LRN", "L2Normalization", "UpSampling", "BilinearResize2D",
           "AdaptiveAvgPooling2D",
           "Crop", "SliceChannel", "ROIPooling", "GridGenerator",
           "BilinearSampler", "SpatialTransformer", "Correlation",
           "MakeLoss", "BlockGrad", "stop_gradient", "batch_take",
           "ravel_multi_index", "unravel_index", "digamma", "khatri_rao",
           "moments"]


# --------------------------------------------------------------- kernels
def lrn_k(x, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Across-channel local response norm, NCHW (reference: lrn.cc):
    out = x / (knorm + alpha/n * sum_{window} x^2)^beta. The channel
    window sum is a static stack of shifted slices — one fused region."""
    half = nsize // 2
    sq = x * x
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    c = x.shape[1]
    win = sum(pad[:, i:i + c] for i in range(nsize))
    return x / jnp.power(knorm + (alpha / nsize) * win, beta)


def l2_normalization_k(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, x.ndim))
    else:
        raise MXNetError(f"L2Normalization: unknown mode {mode!r}")
    norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + eps)
    return x / norm


def upsampling_k(x, scale=2, sample_type="nearest"):
    n, c, h, w = x.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
    if sample_type == "bilinear":
        return jax.image.resize(x, (n, c, h * scale, w * scale),
                                method="bilinear")
    raise MXNetError(f"UpSampling: unknown sample_type {sample_type!r}")


def bilinear_resize_k(x, height, width):
    n, c = x.shape[:2]
    return jax.image.resize(x, (n, c, height, width), method="bilinear")


def _adaptive_pool_matrix(in_size, out_size):
    """(out, in) averaging matrix for adaptive pooling: output cell i
    averages input rows floor(i*I/O) .. ceil((i+1)*I/O)-1 — the upstream
    region rule (src/operator/contrib/adaptive_avg_pooling-inl.h). Built
    with host numpy at trace time (shapes are static under jit), so the
    pool lowers to a matmul the MXU eats directly."""
    import numpy as onp
    m = onp.zeros((out_size, in_size), onp.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -((-(i + 1) * in_size) // out_size)  # ceil
        m[i, lo:hi] = 1.0 / (hi - lo)
    return m


def adaptive_avg_pool2d_k(x, output_size):
    """NCHW adaptive average pool to (OH, OW) (reference:
    contrib.AdaptiveAvgPooling2D). Implemented as two dense contractions
    out = Mh @ x @ Mw^T rather than a gather loop — static pooling
    matrices, MXU-friendly."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = int(output_size[0]), int(output_size[1])
    h, w = x.shape[2], x.shape[3]
    # weights stay float32 (1/3 in bf16 costs ~2e-3 before the einsum
    # even runs; integer dtypes would truncate them to 0) and HIGHEST
    # keeps the MXU pass off bf16; only the result drops back to x.dtype
    mh = jnp.asarray(_adaptive_pool_matrix(h, oh))
    mw = jnp.asarray(_adaptive_pool_matrix(w, ow))
    out = jnp.einsum("nchw,oh,pw->ncop", x.astype(jnp.float32), mh, mw,
                     precision=jax.lax.Precision.HIGHEST)
    return out.astype(x.dtype)


def crop_k(x, h_w=None, offset=(0, 0), like_shape=None, center_crop=False):
    th, tw = like_shape[2:] if like_shape is not None else h_w
    if center_crop:
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        oy, ox = offset
    return x[:, :, oy:oy + th, ox:ox + tw]


def batch_take_k(a, idx):
    return jnp.take_along_axis(
        a, idx.astype(jnp.int32).reshape(-1, 1), axis=1)[:, 0]


def grid_generator_k(affine, target_shape):
    """(N, 6) affine -> (N, 2, H, W) normalised sampling grid in [-1, 1]
    (reference: GridGenerator affine mode; row 0 = x coords, row 1 = y)."""
    h, w = target_shape
    theta = affine.reshape(-1, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, HW)
    out = jnp.einsum("nij,jk->nik", theta, base)              # (N, 2, HW)
    return out.reshape(-1, 2, h, w)


def bilinear_sampler_k(data, grid):
    """Sample NCHW `data` at `grid` (N, 2, Ho, Wo) of [-1, 1] coords
    (reference: BilinearSampler). Out-of-range samples clamp to the border
    after zero-weighting, matching the reference's zero padding."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * 0.5 * (w - 1)
    gy = (grid[:, 1] + 1.0) * 0.5 * (h - 1)
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yi, xi):
        valid = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = data.reshape(n, c, h * w)
        idx = (yc * w + xc).reshape(n, 1, -1)
        vals = jnp.take_along_axis(
            flat, jnp.broadcast_to(idx, (n, c, idx.shape[-1])), axis=2)
        vals = vals.reshape(n, c, *gx.shape[1:])
        return vals * valid[:, None].astype(data.dtype)

    out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[:, None]
           + gather(y0, x0 + 1) * (wx * (1 - wy))[:, None]
           + gather(y0 + 1, x0) * ((1 - wx) * wy)[:, None]
           + gather(y0 + 1, x0 + 1) * (wx * wy)[:, None])
    return out.astype(data.dtype)


def spatial_transformer_k(data, affine, target_shape):
    """STN = GridGenerator + BilinearSampler (reference:
    spatial_transformer.cc, affine/ bilinear mode only — same as cuDNN)."""
    return bilinear_sampler_k(data, grid_generator_k(affine, target_shape))


def _round_half_away(x):
    # C round(): ties away from zero (jnp.round is half-to-even)
    return jnp.where(x >= 0, jnp.floor(x + 0.5), jnp.ceil(x - 0.5))


def roi_pooling_k(data, rois, pooled_size, spatial_scale):
    """Max pooling over quantised ROI bins (reference: roi_pooling.cc).
    data (N, C, H, W); rois (R, 5) = [batch_idx, x1, y1, x2, y2] in input
    coords. Masked-max formulation (static shapes; see module docstring).
    Bin windows clamp to the image like the reference; empty bins emit 0."""
    ph, pw = pooled_size
    n, c, h, w = data.shape

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = _round_half_away(roi[1] * spatial_scale)
        y1 = _round_half_away(roi[2] * spatial_scale)
        x2 = _round_half_away(roi[3] * spatial_scale)
        y2 = _round_half_away(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bh, bw = rh / ph, rw / pw
        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        ys0 = jnp.clip(jnp.floor(y1 + iy * bh), 0, h)
        ys1 = jnp.clip(jnp.ceil(y1 + (iy + 1) * bh), 0, h)
        xs0 = jnp.clip(jnp.floor(x1 + ix * bw), 0, w)
        xs1 = jnp.clip(jnp.ceil(x1 + (ix + 1) * bw), 0, w)
        # masks: (ph, H) and (pw, W)
        my = (ys[None] >= ys0[:, None]) & (ys[None] < ys1[:, None])
        mx_ = (xs[None] >= xs0[:, None]) & (xs[None] < xs1[:, None])
        mask = my[:, None, :, None] & mx_[None, :, None, :]  # (ph,pw,H,W)
        img = data[bidx]                                      # (C, H, W)
        neg = jnp.asarray(-jnp.inf, data.dtype)
        vals = jnp.where(mask[:, :, None], img[None, None], neg)
        out = jnp.max(vals, axis=(3, 4))                      # (ph, pw, C)
        out = jnp.where(jnp.isfinite(out), out, 0.0)  # empty bin -> 0
        return jnp.transpose(out, (2, 0, 1)).astype(data.dtype)

    return jax.vmap(one_roi)(rois.astype(jnp.float32))


def correlation_k(a, b, kernel_size=1, max_displacement=4, stride1=1,
                  stride2=1, is_multiply=True):
    """FlowNet-style correlation (reference: correlation.cc):
    out[:, k, y, x] = mean_c a[:, c, y, x] (*|abs-diff) b_shifted_k for
    each displacement k stepped by `stride2` in a (2d+1)^2 window, output
    spatially subsampled by `stride1` — a static stack of shifted
    elementwise products. kernel_size=1 only (the FlowNet configuration)."""
    if kernel_size != 1:
        raise MXNetError("Correlation: kernel_size != 1 not supported "
                         "(FlowNet uses 1; larger kernels need a patch "
                         "reduction the reference rarely exercises)")
    d = max_displacement
    n, c, h, w = a.shape
    pad_b = jnp.pad(b, ((0, 0), (0, 0), (d, d), (d, d)))
    outs = []
    for dy in range(-d, d + 1, stride2):
        for dx in range(-d, d + 1, stride2):
            shifted = pad_b[:, :, d + dy:d + dy + h, d + dx:d + dx + w]
            prod = a * shifted if is_multiply else jnp.abs(a - shifted)
            outs.append(jnp.mean(prod, axis=1))
    out = jnp.stack(outs, axis=1)
    return out[:, :, ::stride1, ::stride1]


# ---------------------------------------------------- autograd-shaping ops
import functools


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _make_loss(x, grad_scale):
    return x


def _ml_fwd(x, grad_scale):
    return x, x  # residual keeps the aval for shape/dtype


def _ml_bwd(grad_scale, res, g):
    # the node IS the loss: incoming cotangent is ignored, gradient is
    # grad_scale everywhere (reference: make_loss.cc)
    return (jnp.full(res.shape, grad_scale, res.dtype),)


_make_loss.defvjp(_ml_fwd, _ml_bwd)


def make_loss_k(x, grad_scale=1.0):
    return _make_loss(x, grad_scale)


# ------------------------------------------------- imperative nd wrappers
def LRN(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    return _apply(lambda x: lrn_k(x, alpha, beta, knorm, nsize), [data])


def L2Normalization(data, eps=1e-10, mode="instance", **kw):
    return _apply(lambda x: l2_normalization_k(x, eps, mode), [data])


def UpSampling(data, scale=2, sample_type="nearest", num_filter=0, **kw):
    return _apply(lambda x: upsampling_k(x, scale, sample_type), [data])


def _resize_target(shape, height, width, scale_height, scale_width):
    """Resolve the (H, W) target from explicit sizes or upstream's
    scale_height/scale_width mode (bilinear_resize-inl.h)."""
    h = int(height) if height else (
        int(round(shape[2] * scale_height)) if scale_height else 0)
    w = int(width) if width else (
        int(round(shape[3] * scale_width)) if scale_width else 0)
    if h <= 0 or w <= 0:
        raise MXNetError("BilinearResize2D: need height+width or "
                         "scale_height+scale_width")
    return h, w


def BilinearResize2D(data, height=None, width=None, scale_height=None,
                     scale_width=None, **kw):
    def fn(x):
        h, w = _resize_target(x.shape, height, width,
                              scale_height, scale_width)
        return bilinear_resize_k(x, h, w)
    return _apply(fn, [data])


def AdaptiveAvgPooling2D(data, output_size=1, **kw):
    """reference: contrib.AdaptiveAvgPooling2D (NCHW)."""
    return _apply(lambda x: adaptive_avg_pool2d_k(x, output_size), [data])


def Crop(data, crop_like=None, h_w=None, offset=(0, 0),
         center_crop=False, **kw):
    if crop_like is not None:
        return _apply(lambda x, y: crop_k(x, like_shape=y.shape,
                                          offset=offset,
                                          center_crop=center_crop),
                      [data, crop_like])
    return _apply(lambda x: crop_k(x, h_w=h_w, offset=offset,
                                   center_crop=center_crop), [data])


def SliceChannel(data, num_outputs, axis=1, squeeze_axis=False, **kw):
    """reference: slice_channel.cc (a.k.a. split)."""
    def fn(x):
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    return _apply(fn, [data], n_out=num_outputs)


def ROIPooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0, **kw):
    return _apply(lambda x, r: roi_pooling_k(x, r, pooled_size,
                                             spatial_scale), [data, rois])


def GridGenerator(data, transform_type="affine", target_shape=None, **kw):
    if transform_type != "affine":
        raise MXNetError("GridGenerator: only affine mode (like cuDNN)")
    return _apply(lambda a: grid_generator_k(a, target_shape), [data])


def BilinearSampler(data, grid, **kw):
    return _apply(bilinear_sampler_k, [data, grid])


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type="affine", sampler_type="bilinear",
                       **kw):
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer: affine+bilinear only "
                         "(the cuDNN-supported mode)")
    return _apply(lambda x, a: spatial_transformer_k(x, a, target_shape),
                  [data, loc])


def Correlation(data1, data2, kernel_size=1, max_displacement=4, stride1=1,
                stride2=1, is_multiply=True, **kw):
    return _apply(lambda a, b: correlation_k(
        a, b, kernel_size=kernel_size, max_displacement=max_displacement,
        stride1=stride1, stride2=stride2, is_multiply=is_multiply),
        [data1, data2])


def MakeLoss(data, grad_scale=1.0, **kw):
    return _apply(lambda x: make_loss_k(x, grad_scale), [data])


def BlockGrad(data, **kw):
    return _apply(jax.lax.stop_gradient, [data])


stop_gradient = BlockGrad


def batch_take(a, indices, **kw):
    return _apply(batch_take_k, [a, indices])


def ravel_multi_index(data, shape=None, **kw):
    def fn(x):
        idx = tuple(x[i].astype(jnp.int32) for i in range(x.shape[0]))
        return jnp.ravel_multi_index(idx, shape, mode="clip").astype(
            jnp.float32)
    return _apply(fn, [data])


def unravel_index(data, shape=None, **kw):
    def fn(x):
        out = jnp.unravel_index(x.astype(jnp.int32), shape)
        return jnp.stack(out).astype(jnp.float32)
    return _apply(fn, [data])


def digamma(data, **kw):
    return _apply(jax.scipy.special.digamma, [data])


def khatri_rao(*matrices, **kw):
    """Column-wise Kronecker product (reference: contrib/krprod.cc,
    `mx.nd.khatri_rao`). Inputs (n_i, k) with a shared column count k;
    output (prod n_i, k). One einsum per pair -> a single fused XLA
    contraction chain, no per-column loops."""
    if not matrices:
        raise MXNetError("khatri_rao: need at least one matrix")

    def fn(*ms):
        out = ms[0]
        for m in ms[1:]:
            if m.shape[-1] != out.shape[-1]:
                raise MXNetError(
                    "khatri_rao: column counts differ "
                    f"({out.shape[-1]} vs {m.shape[-1]})")
            out = jnp.einsum("ik,jk->ijk", out, m).reshape(
                out.shape[0] * m.shape[0], m.shape[-1])
        return out
    return _apply(fn, list(matrices))


def moments(data, axes=None, keepdims=False, **kw):
    """Mean and variance along `axes` (reference: nn/moments.cc). Returns
    (mean, var) computed in one pass — XLA fuses both reductions over a
    single read of the input."""
    if axes is None:
        ax = None
    else:
        ax = tuple(axes) if isinstance(axes, (list, tuple)) else (axes,)

    def fn(x):
        mean = jnp.mean(x, axis=ax, keepdims=True)
        var = jnp.mean((x - mean) * (x - mean), axis=ax, keepdims=True)
        if not keepdims:
            mean = jnp.squeeze(mean, axis=ax)
            var = jnp.squeeze(var, axis=ax)
        return mean, var
    return _apply(fn, [data], n_out=2)
