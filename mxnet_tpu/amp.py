"""Automatic mixed precision (reference: python/mxnet/contrib/amp).

TPU-native: bf16 is the native MXU dtype (no loss scaling needed, unlike
fp16 on GPUs), so `init()` casts compute-heavy layers to bfloat16 while
keeping norms/softmax in fp32. A DynamicLossScaler is provided for fp16
parity with the reference's amp.scale_loss / amp.unscale API.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["init", "convert_block", "scale_loss", "unscale",
           "DynamicLossScaler", "bfloat16"]

bfloat16 = jnp.bfloat16

_CAST_LAYERS = ("Dense", "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
                "Embedding")
_KEEP_FP32 = ("BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm")

_state = {"scaler": None, "initialized": False}


def init(target_dtype="bfloat16"):
    """Enable AMP defaults (reference: amp.init())."""
    _state["initialized"] = True
    _state["target_dtype"] = target_dtype
    if target_dtype == "float16":
        _state["scaler"] = DynamicLossScaler()


def convert_block(block, target_dtype="bfloat16"):
    """Cast matmul/conv layers to bf16, keep normalisation fp32
    (reference: amp.convert_hybrid_block)."""
    def walk(b):
        name = type(b).__name__
        if name in _CAST_LAYERS:
            b.cast(target_dtype)
        for c in b._children.values():
            walk(c)
    walk(block)
    return block


class DynamicLossScaler:
    """Reference: AMP dynamic loss scaling (fp16 only; bf16 doesn't need it)."""

    def __init__(self, init_scale=2. ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params):
        for p in params:
            if p._grad is not None:
                g = p._grad.asnumpy()
                if not np.isfinite(g).all():
                    return True
        return False

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self.scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0


def scale_loss(loss, trainer_or_scaler=None):
    scaler = _state.get("scaler")
    if scaler is None:
        return loss
    return loss * scaler.loss_scale


def unscale(grads_or_trainer):
    scaler = _state.get("scaler")
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    params = grads_or_trainer._params if hasattr(grads_or_trainer, "_params") \
        else grads_or_trainer
    for p in params:
        if getattr(p, "_grad", None) is not None:
            p._grad._rebind(p._grad._data * inv)
