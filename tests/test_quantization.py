"""INT8 quantization tests (SURVEY.md §2 #49; reference:
tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.contrib import quantization as q
from mxnet_tpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = nd.array(np.linspace(-2.0, 2.0, 64).astype(np.float32))
    xq, mn, mx_ = q.quantize(x)
    assert "int8" in str(xq.dtype)
    back = q.dequantize(xq, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=2.0 / 127)


def test_quantized_dense_matches_fp():
    mx.random.seed(0)
    dense = nn.Dense(16, in_units=32)
    dense.initialize()
    qd = q.QuantizedDense(dense)
    assert str(qd.wq.dtype) == "int8"
    x = nd.random.uniform(-1, 1, shape=(4, 32))
    y_fp = dense(x).asnumpy()
    y_q = qd(x).asnumpy()
    # int8 symmetric: ~1% of dynamic range
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantized_conv_matches_fp():
    mx.random.seed(1)
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=4)
    conv.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4, 8, 8))
    y_fp = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv)
    y_q = qc(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantize_net_end_to_end():
    mx.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(10, in_units=32))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(8, 16))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net)
    assert len(qnet.quantized_layers) == 2
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err
    # argmax (classification decision) should essentially agree
    agree = (y_fp.argmax(1) == y_q.argmax(1)).mean()
    assert agree >= 0.75


def test_quantize_net_calibration_freezes_scales():
    mx.random.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4))
    net.initialize()
    calib = [nd.random.uniform(-1, 1, shape=(4, 4)) for _ in range(3)]
    qnet = q.quantize_net(net, calib_data=calib, num_calib_batches=3)
    (layer,) = qnet.quantized_layers
    assert layer._act_scale is not None and layer._act_scale > 0
    x = nd.random.uniform(-1, 1, shape=(4, 4))
    err = np.abs(net(x).asnumpy() - qnet(x).asnumpy()).max()
    assert err < 0.1


def test_quantize_net_exclude_layers():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    qnet = q.quantize_net(net, exclude_layers=["1"])
    assert len(qnet.quantized_layers) == 1


def test_quantize_net_no_quantizable_raises():
    net = nn.HybridSequential()
    net.add(nn.Dropout(0.5))
    with pytest.raises(Exception):
        q.quantize_net(net)


def test_quantize_net_nested_sequential():
    """Nested Sequential containers are rewired too (not silently fp)."""
    mx.random.seed(4)
    inner = nn.HybridSequential()
    inner.add(nn.Dense(16, activation="relu", in_units=8))
    net = nn.HybridSequential()
    net.add(inner, nn.Dense(4, in_units=16))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(4, 8))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net)
    assert len(qnet.quantized_layers) == 2
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err


def test_quantize_net_custom_block_supported():
    """Quantizable layers inside CUSTOM blocks are rewired too (r3 weak 3:
    the old implementation refused anything but Sequential trees)."""
    mx.random.seed(6)

    class Custom(nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.fc = nn.Dense(4, in_units=4)

        def hybrid_forward(self, F, x):
            return self.fc(x) + x          # residual: not a plain chain

    net = nn.HybridSequential()
    net.add(Custom())
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net)
    assert len(qnet.quantized_layers) == 1
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err
    # the ORIGINAL net still runs fp32 when called directly
    np.testing.assert_allclose(net(x).asnumpy(), y_fp, rtol=1e-6)


def test_quantize_net_zoo_resnet18():
    """The obvious int8 target works end to end: quantize_net over a zoo
    resnet18 (custom residual HybridBlocks), classification decisions
    within 1% of fp32 on synthetic data (VERDICT r3 item 4 done-bar)."""
    mx.random.seed(7)
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    net = resnet18_v1(classes=10)
    net.initialize()
    x = nd.random.uniform(0, 1, shape=(8, 3, 32, 32))
    y_fp = net(x).asnumpy()
    qnet = q.quantize_net(net, calib_data=[x], calib_mode="naive")
    assert len(qnet.quantized_layers) >= 18   # convs + fc
    y_q = qnet(x).asnumpy()
    agree = (y_fp.argmax(1) == y_q.argmax(1)).mean()
    assert agree >= 0.99, agree
    rel = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert rel < 0.15, rel


def test_entropy_calibration_beats_naive_on_skewed_activations():
    """A heavy-tailed input (one huge outlier) wrecks max-abs scaling;
    the KL threshold clips the tail and must reconstruct the bulk better
    (VERDICT r3 item 4 done-bar)."""
    mx.random.seed(8)
    rs = np.random.RandomState(0)
    bulk = rs.uniform(-1, 1, size=(256, 32)).astype(np.float32)
    bulk[0, 0] = 80.0           # lone outlier -> naive scale 80/127
    dense = nn.Dense(16, in_units=32)
    dense.initialize()

    def quantize_with(mode):
        net = nn.HybridSequential()
        net.add(dense)
        qnet = q.quantize_net(net, calib_data=[nd.array(bulk)],
                              calib_mode=mode)
        (layer,) = qnet.quantized_layers
        return qnet, layer

    _, naive_layer = quantize_with("naive")
    q_ent, ent_layer = quantize_with("entropy")
    assert ent_layer._act_scale < naive_layer._act_scale * 0.5, \
        (ent_layer._act_scale, naive_layer._act_scale)
    # reconstruction of the BULK is tighter under the entropy scale
    x_eval = nd.array(rs.uniform(-1, 1, size=(64, 32)).astype(np.float32))
    y_fp = dense(x_eval).asnumpy()
    err_ent = np.abs(q_ent(x_eval).asnumpy() - y_fp).mean()
    s_naive = float(naive_layer._act_scale)
    # naive error floor ~ uniform quantization noise at scale 80/127
    assert err_ent < s_naive, (err_ent, s_naive)


def test_kl_threshold_closed_form():
    """Decaying bulk + lone outlier -> threshold well below amax (coarse
    128-level merges can't reconstruct a non-uniform bulk, clipping can)."""
    hist = np.zeros(2048)
    hist[:128] = np.linspace(1000.0, 10.0, 128)   # decaying bulk
    hist[-1] = 1.0                                 # outlier at amax
    t = q.kl_optimal_threshold(hist, amax=80.0)
    assert t < 20.0, t
    # uniform histogram -> keep (close to) the full range
    t_full = q.kl_optimal_threshold(np.ones(2048), amax=1.0)
    assert t_full > 0.9


def test_uint8_activations_zero_point_decomposition():
    """quantized_dtype='uint8' on non-negative activations: the int8
    MXU path + 128-correction must match fp32 within uint8 resolution,
    and beat int8 resolution on the same data."""
    mx.random.seed(9)
    dense = nn.Dense(16, in_units=32)
    dense.initialize()
    x = nd.random.uniform(0, 1, shape=(64, 32))    # post-relu-like
    net = nn.HybridSequential()
    net.add(dense)
    y_fp = dense(x).asnumpy()

    q_u8 = q.quantize_net(net, quantized_dtype="uint8", calib_data=[x])
    (l_u8,) = q_u8.quantized_layers
    assert l_u8._act_unsigned
    err_u8 = np.abs(q_u8(x).asnumpy() - y_fp).mean()

    q_s8 = q.quantize_net(net, quantized_dtype="int8", calib_data=[x])
    err_s8 = np.abs(q_s8(x).asnumpy() - y_fp).mean()
    assert err_u8 < err_s8, (err_u8, err_s8)

    # 'auto' picks uint8 for non-negative ranges
    q_auto = q.quantize_net(net, quantized_dtype="auto", calib_data=[x])
    (l_auto,) = q_auto.quantized_layers
    assert l_auto._act_unsigned


def test_uint8_conv_border_correction():
    """The zero-point correction map is border-aware under zero padding:
    a padded uint8 conv must still match fp32 at the edges."""
    mx.random.seed(10)
    conv = nn.Conv2D(4, kernel_size=3, padding=1, in_channels=2)
    conv.initialize()
    x = nd.random.uniform(0, 1, shape=(2, 2, 6, 6))
    net = nn.HybridSequential()
    net.add(conv)
    y_fp = conv(x).asnumpy()
    qnet = q.quantize_net(net, quantized_dtype="uint8", calib_data=[x])
    y_q = qnet(x).asnumpy()
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantize_net_inside_hybridize_trace():
    """A hybridized parent jit-traces THROUGH the routers: int8 math in
    the compiled executable, and mode-private caches keep fp32/int8
    executables separate."""
    mx.random.seed(11)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4),
            nn.Dense(2, in_units=8))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(4, 4))
    y_fp_pre = net(x).asnumpy()
    net.hybridize()
    net(x)                       # build the fp32 compiled cache
    qnet = q.quantize_net(net)
    y_q = qnet(x).asnumpy()
    y_fp_post = net(x).asnumpy()       # original net: still fp32 math
    np.testing.assert_allclose(y_fp_post, y_fp_pre, rtol=1e-5, atol=1e-6)
    assert np.abs(y_q - y_fp_pre).max() > 0  # actually quantized
    err = np.abs(y_q - y_fp_pre).max() / (np.abs(y_fp_pre).max() + 1e-6)
    assert err < 0.1, err


def test_quantized_conv_dilation_and_groups():
    mx.random.seed(5)
    conv = nn.Conv2D(8, kernel_size=3, padding=2, dilation=2, groups=2,
                     in_channels=4)
    conv.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4, 8, 8))
    y_fp = conv(x).asnumpy()
    qc = q.QuantizedConv2D(conv)
    y_q = qc(x).asnumpy()
    assert y_q.shape == y_fp.shape
    err = np.abs(y_fp - y_q).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.05, err


def test_quantized_dense_sigmoid_activation():
    dense = nn.Dense(4, activation="sigmoid", in_units=4)
    dense.initialize()
    x = nd.random.uniform(-1, 1, shape=(2, 4))
    y_fp = dense(x).asnumpy()
    y_q = q.QuantizedDense(dense)(x).asnumpy()
    np.testing.assert_allclose(y_fp, y_q, atol=0.02)


def test_calibration_on_hybridized_net():
    """Calibration must not run inside a jit trace (observe() reads
    concrete values): a pre-hybridized, pre-compiled net calibrates fine
    and then runs int8 through the compiled path."""
    mx.random.seed(12)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4))
    net.initialize()
    x = nd.random.uniform(-1, 1, shape=(128, 4))
    net.hybridize()
    net(x)                        # compiled fp32 cache exists
    qnet = q.quantize_net(net, calib_data=[x], calib_mode="entropy")
    (layer,) = qnet.quantized_layers
    assert layer._act_scale is not None
    y_q = qnet(x).asnumpy()
    y_fp = net(x).asnumpy()
    err = np.abs(y_q - y_fp).max() / (np.abs(y_fp).max() + 1e-6)
    assert err < 0.1, err
    # hybridization flags restored after calibration
    assert net._active


def test_uint8_conv_no_tracer_leak_across_jit_boundary():
    """The +128 correction map computed inside a jit trace must not be
    cached and served to a later EAGER call of the same shape."""
    mx.random.seed(13)
    conv = nn.Conv2D(4, kernel_size=3, padding=1, in_channels=2)
    conv.initialize()
    net = nn.HybridSequential()
    net.add(conv)
    x = nd.random.uniform(0, 1, shape=(1, 2, 5, 5))
    qnet = q.quantize_net(net, quantized_dtype="uint8", calib_data=[x])
    net.hybridize()
    y_jit = qnet(x).asnumpy()       # populates nothing tracer-shaped...
    net.hybridize(False)
    y_eager = qnet(x).asnumpy()     # ...or this raises UnexpectedTracer
    np.testing.assert_allclose(y_jit, y_eager, rtol=1e-5, atol=1e-6)


def test_uint8_requires_calibrating_mode():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=4))
    net.initialize()
    x = nd.random.uniform(0, 1, shape=(2, 4))
    with pytest.raises(Exception, match="calib_mode"):
        q.quantize_net(net, quantized_dtype="uint8", calib_data=[x],
                       calib_mode=None)


def test_quantize_net_multi_input_bert():
    """Multi-input nets quantize too (reference upstream only feeds
    batch[0]; calib_inputs=k feeds the first k tuple elements): BERT-mini
    int8 inference stays within 1% of fp32 on the pooled output, with
    every Dense in the encoder (qkv/proj/ffn/pooler) rewired."""
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.models.bert import BERTModel
    net = BERTModel(vocab_size=60, units=32, hidden_size=64, num_layers=2,
                    num_heads=4, max_length=16, dropout=0.0)
    net.initialize()
    rng = np.random.RandomState(0)
    tok = nd.array(rng.randint(0, 60, (2, 12)).astype(np.float32))
    seg = nd.array(np.zeros((2, 12), np.float32))
    _, ref_pool = net(tok, seg)
    q = quantize_net(net, quantized_dtype="int8",
                     calib_data=[(tok, seg)], calib_mode="naive",
                     calib_inputs=2)
    assert len(q.quantized_layers) >= 2 * 4 + 2  # per-layer qkv/proj/ffn1/2
    _, qp = q(tok, seg)
    rel = float(np.abs(qp.asnumpy() - ref_pool.asnumpy()).max()) / \
        float(np.abs(ref_pool.asnumpy()).max())
    assert rel < 0.01, rel
    # fp32 behaviour of the source net is untouched
    _, again = net(tok, seg)
    np.testing.assert_allclose(again.asnumpy(), ref_pool.asnumpy())


# ---- op-level quantization surface (VERDICT r4 item 5; upstream:
# src/operator/quantization/*.cc) ---------------------------------------
def test_nd_contrib_quantize_int8_closed_form():
    rs = np.random.RandomState(0)
    x = rs.randn(5, 7).astype(np.float32) * 3
    q, mn, mx = nd.contrib.quantize(nd.array(x), nd.array([-4.0]),
                                    nd.array([4.0]), out_type="int8")
    assert q.dtype == np.int8
    want = np.clip(np.round(x * 127.0 / 4.0), -127, 127)
    np.testing.assert_allclose(q.asnumpy(), want)
    assert float(mn.asnumpy()) == -4.0 and float(mx.asnumpy()) == 4.0


def test_nd_contrib_quantize_uint8_affine():
    rs = np.random.RandomState(1)
    x = rs.rand(4, 6).astype(np.float32)  # [0, 1)
    q, mn, mx = nd.contrib.quantize(nd.array(x), nd.array([0.0]),
                                    nd.array([1.0]), out_type="uint8")
    assert q.dtype == np.uint8
    np.testing.assert_allclose(q.asnumpy(),
                               np.clip(np.round(x * 255.0), 0, 255))
    back = nd.contrib.dequantize(q, mn, mx).asnumpy()
    np.testing.assert_allclose(back, x, atol=1.0 / 255.0)


def test_quantize_v2_dynamic_and_calibrated():
    rs = np.random.RandomState(2)
    x = rs.randn(8, 8).astype(np.float32)
    # dynamic: range from data
    q, mn, mx = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    amax = np.abs(x).max()
    np.testing.assert_allclose(float(mx.asnumpy()), amax, rtol=1e-6)
    np.testing.assert_allclose(
        q.asnumpy(), np.clip(np.round(x * 127.0 / amax), -127, 127))
    # calibrated: attr range wins
    q2, mn2, mx2 = nd.contrib.quantize_v2(
        nd.array(x), out_type="int8", min_calib_range=-2.0,
        max_calib_range=2.0)
    np.testing.assert_allclose(
        q2.asnumpy(), np.clip(np.round(x * 127.0 / 2.0), -127, 127))


def test_quantize_v2_dequantize_matches_quantize_net_math():
    """The op pair reproduces the graph-level quantize_net layer math
    (contrib/quantization.py _scale_of: symmetric absmax/127)."""
    from mxnet_tpu.contrib import quantization as qz
    rs = np.random.RandomState(3)
    x = rs.randn(6, 6).astype(np.float32)
    q, mn, mx = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    ops_back = nd.contrib.dequantize(q, mn, mx).asnumpy()
    gq, gmn, gmx = qz.quantize(nd.array(x))
    graph_back = qz.dequantize(gq, gmn, gmx).asnumpy()
    np.testing.assert_allclose(ops_back, graph_back, atol=1e-6)


def test_requantize_int32_to_int8():
    """int32 accumulator -> int8: matches dequantize-then-requantize
    closed form, calibrated and dynamic."""
    rs = np.random.RandomState(4)
    f = np.clip(rs.randn(5, 5) * 30, -79, 79).astype(np.float32)
    amax32 = 80.0
    q32 = np.round(f.astype(np.float64) * (2**31 - 1) / amax32) \
        .astype(np.int64).astype(np.int32)
    q8, mn, mx = nd.contrib.requantize(
        nd.array(q32), nd.array([-amax32]), nd.array([amax32]))
    fb = q32.astype(np.float64) * amax32 / (2**31 - 1)
    want = np.clip(np.round(fb * 127.0 / np.abs(fb).max()), -127, 127)
    np.testing.assert_allclose(q8.asnumpy(), want)
    q8c, mnc, mxc = nd.contrib.requantize(
        nd.array(q32), nd.array([-amax32]), nd.array([amax32]),
        min_calib_range=-60.0, max_calib_range=60.0)
    wantc = np.clip(np.round(fb * 127.0 / 60.0), -127, 127)
    np.testing.assert_allclose(q8c.asnumpy(), wantc)
    assert float(mxc.asnumpy()) == 60.0


def test_sym_contrib_quantize_json_roundtrip():
    """The full sym chain quantize_v2 -> dequantize survives JSON and
    matches the nd path."""
    rs = np.random.RandomState(5)
    x = rs.randn(4, 4).astype(np.float32)
    d = sym.Variable("data")
    qsym = sym.contrib.quantize_v2(d, out_type="int8",
                                   min_calib_range=-3.0,
                                   max_calib_range=3.0)
    deq = sym.contrib.dequantize(qsym[0], qsym[1], qsym[2])
    loaded = mx.sym.load_json(deq.tojson())
    out = loaded.bind(mx.cpu(), {"data": nd.array(x)}).forward()[0]
    q, mn, mx_ = nd.contrib.quantize_v2(nd.array(x), out_type="int8",
                                        min_calib_range=-3.0,
                                        max_calib_range=3.0)
    want = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    np.testing.assert_allclose(out.asnumpy(), want, atol=1e-6)
    # quantize with tensor ranges round-trips too
    qs = sym.contrib.quantize(sym.Variable("data"), sym.Variable("mn"),
                              sym.Variable("mx"), out_type="uint8")
    loaded2 = mx.sym.load_json(qs.tojson())
    outs = loaded2.bind(mx.cpu(), {"data": nd.array(np.abs(x)),
                                   "mn": nd.array([0.0]),
                                   "mx": nd.array([4.0])}).forward()
    ref_q, _, _ = nd.contrib.quantize(nd.array(np.abs(x)),
                                      nd.array([0.0]), nd.array([4.0]),
                                      out_type="uint8")
    np.testing.assert_allclose(outs[0].asnumpy(), ref_q.asnumpy())


def test_quantized_fully_connected_end_to_end():
    """quantize_v2 -> quantized_fully_connected -> dequantize ~= float FC
    within quantization error (upstream quantized_fully_connected.cc)."""
    rs = np.random.RandomState(6)
    x = rs.randn(8, 32).astype(np.float32)
    w = (rs.randn(16, 32) * 0.2).astype(np.float32)
    b = rs.randn(16).astype(np.float32)
    xq, xmn, xmx = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    wq, wmn, wmx = nd.contrib.quantize_v2(nd.array(w), out_type="int8")
    acc, omn, omx = nd.contrib.quantized_fully_connected(
        xq, wq, nd.array(b), xmn, xmx, wmn, wmx, num_hidden=16)
    assert acc.asnumpy().dtype == np.int32
    out = nd.contrib.dequantize(acc, omn, omx).asnumpy()
    ref = x @ w.T + b
    # error bound: K * (sx*|w| + sw*|x|) rounding terms; loose 2% rel
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.02
    # int8 deploy chain continues: requantize to int8 with the observed
    # float range, dequantize, same answer within int8 resolution
    amax = float(np.abs(ref).max()) * 1.05
    q8, qmn, qmx = nd.contrib.requantize(acc, omn, omx,
                                         min_calib_range=-amax,
                                         max_calib_range=amax)
    out8 = nd.contrib.dequantize(q8, qmn, qmx).asnumpy()
    assert np.abs(out8 - ref).max() <= amax / 127 * 0.51 + 0.02 * np.abs(ref).max()


def test_quantized_conv_matches_float():
    rs = np.random.RandomState(7)
    x = rs.randn(2, 3, 10, 10).astype(np.float32)
    w = (rs.randn(8, 3, 3, 3) * 0.2).astype(np.float32)
    xq, xmn, xmx = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    wq, wmn, wmx = nd.contrib.quantize_v2(nd.array(w), out_type="int8")
    acc, omn, omx = nd.contrib.quantized_conv(
        xq, wq, None, xmn, xmx, wmn, wmx, kernel=(3, 3), pad=(1, 1),
        no_bias=True)
    out = nd.contrib.dequantize(acc, omn, omx).asnumpy()
    import jax.numpy as jnp
    from jax import lax
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    ref = np.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=dn))
    assert np.abs(out - ref).max() / np.abs(ref).max() < 0.03


def test_quantized_pooling_and_flatten():
    rs = np.random.RandomState(8)
    x = rs.randn(2, 4, 8, 8).astype(np.float32)
    xq, lo, hi = nd.contrib.quantize_v2(nd.array(x), out_type="int8")
    # max-pool commutes with the monotone quantize map exactly
    pq, pmn, pmx = nd.contrib.quantized_pooling(xq, lo, hi,
                                                kernel=(2, 2),
                                                pool_type="max")
    dq = nd.contrib.dequantize(pq, pmn, pmx).asnumpy()
    ref = x.reshape(2, 4, 4, 2, 4, 2).max((3, 5))
    amax = np.abs(x).max()
    assert np.abs(dq - ref).max() <= amax / 127 * 0.51 + 1e-6
    fq, fmn, fmx = nd.contrib.quantized_flatten(pq, pmn, pmx)
    assert fq.shape == (2, 4 * 4 * 4)
    # sym chain survives JSON
    s = sym.contrib.quantized_pooling(sym.Variable("q"),
                                      sym.Variable("a"),
                                      sym.Variable("b"), kernel=(2, 2),
                                      pool_type="avg")
    g = mx.sym.load_json(s.tojson())
    outs = g.bind(mx.cpu(), {"q": xq, "a": lo, "b": hi}).forward()
    assert outs[0].asnumpy().dtype == np.int8


def test_quantized_pooling_uint8_and_int_attrs():
    """uint8 pooling (identity 0, clip 0..255) and int stride/pad attrs
    through sym (review findings r5)."""
    rs = np.random.RandomState(9)
    x = rs.rand(1, 2, 8, 8).astype(np.float32)
    xq, lo, hi = nd.contrib.quantize(nd.array(x), nd.array([0.0]),
                                     nd.array([1.0]), out_type="uint8")
    pq, pa, pb = nd.contrib.quantized_pooling(xq, lo, hi, kernel=2,
                                              pool_type="max", stride=2)
    assert pq.asnumpy().dtype == np.uint8
    ref = x.reshape(1, 2, 4, 2, 4, 2).max((3, 5))
    back = nd.contrib.dequantize(pq, pa, pb).asnumpy()
    assert np.abs(back - ref).max() <= 1.0 / 255 + 1e-6
    # avg keeps the full uint8 range (no int8 clip)
    aq, _, _ = nd.contrib.quantized_pooling(xq, lo, hi, kernel=2,
                                            pool_type="avg", stride=2)
    assert aq.asnumpy().max() > 127  # would be impossible under int8 clip
    # sym accepts plain ints for kernel/stride/pad
    s = sym.contrib.quantized_pooling(sym.Variable("q"), sym.Variable("a"),
                                      sym.Variable("b"), kernel=2,
                                      pool_type="max", stride=2)
    outs = mx.sym.load_json(s.tojson()).bind(
        mx.cpu(), {"q": xq, "a": lo, "b": hi}).forward()
    np.testing.assert_allclose(outs[0].asnumpy(), pq.asnumpy())
    s2 = sym.contrib.quantized_conv(
        sym.Variable("d"), sym.Variable("w"), None, sym.Variable("a1"),
        sym.Variable("b1"), sym.Variable("a2"), sym.Variable("b2"),
        stride=1, pad=1, no_bias=True)
    assert "_contrib_quantized_conv" in s2.tojson()


def test_quantize_channelwise_per_channel_scales():
    """ISSUE 14: per-channel symmetric int8 — one independent scale per
    index of `axis`, reconstruction error bounded by half a quantisation
    step per channel, zero channels exact."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    w = rng.randn(5, 16).astype(np.float32)
    w[2] *= 100.0          # a hot channel must not coarsen the others
    w[4] = 0.0             # all-zero channel
    wq, scale = q.quantize_channelwise(jnp.asarray(w), axis=0)
    assert wq.dtype == jnp.int8 and scale.shape == (5,)
    rec = np.asarray(wq, np.float32) * np.asarray(scale)[:, None]
    amax = np.abs(w).max(axis=1)
    for c in range(5):
        step = max(amax[c], 1e-12) / 127.0
        assert np.max(np.abs(rec[c] - w[c])) <= step / 2 + 1e-7
    assert np.all(rec[4] == 0.0)
    # per-channel independence: the hot row's scale is ~100x the rest
    s = np.asarray(scale)
    assert s[2] > 20 * s[0]
    # axis=1 variant quantises per input channel
    wq1, scale1 = q.quantize_channelwise(jnp.asarray(w), axis=1)
    assert scale1.shape == (16,)
    rec1 = np.asarray(wq1, np.float32) * np.asarray(scale1)[None, :]
    step1 = np.abs(w).max(axis=0) / 127.0
    assert np.all(np.abs(rec1 - w) <= step1[None, :] / 2 + 1e-7)
