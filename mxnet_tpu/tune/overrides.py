"""Thread-local Pallas knob overrides (ISSUE 20).

The autotuner needs to compile ONE candidate's kernel configuration
without leaking it into every other trace on the process (env vars are
process-global and racy under the engine's background threads). A
`scope(cfg)` context installs a per-thread override dict that
`ops/pallas_kernels.py` consults BEFORE the `MXTPU_*` env knobs; the
env stays the operator-facing fallback, the scope is the tuner-facing
one.

Knob names (values are ints):

  flash_block_q / flash_block_k   flash attention Q/K tile sizes
  rpa_block_k                     ragged-paged-attention sub-page K
                                  block (divides page size, %8 == 0)
  rpa_sublanes                    padded query-row count of the WIDENED
                                  (multi-query verify) RPA launch
                                  (>= W, %8 == 0)

This module is import-light on purpose (stdlib only): pallas_kernels
imports it at module top without creating a cycle.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

__all__ = ["scope", "current", "KNOBS"]

KNOBS = ("flash_block_q", "flash_block_k", "rpa_block_k", "rpa_sublanes")

_tl = threading.local()


def current():
    """The active override dict of THIS thread, or None. Read by the
    kernel block-size pickers at trace time."""
    return getattr(_tl, "cfg", None)


@contextmanager
def scope(cfg):
    """Install `cfg` ({knob: int}) as this thread's Pallas overrides for
    the duration of the block. None / {} is a no-op scope (the tuner's
    baseline candidate). Scopes nest; inner wins wholesale (no merge —
    a candidate IS its full kernel config)."""
    if cfg:
        bad = set(cfg) - set(KNOBS)
        if bad:
            raise ValueError(f"unknown pallas override knob(s): {sorted(bad)}")
    prev = getattr(_tl, "cfg", None)
    _tl.cfg = dict(cfg) if cfg else None
    try:
        yield
    finally:
        _tl.cfg = prev
