"""Data-parallel ResNet training over a device mesh — the fused-step path.

Usage: python examples/data_parallel_resnet.py [--smoke]
On a TPU pod slice this shards the batch over every chip; offline it runs
on the virtual CPU mesh (XLA_FLAGS=--xla_force_host_platform_device_count=8).
The whole train step (fwd+bwd+allreduce+update) is ONE compiled program
with donated buffers — gradients never leave HBM.
"""
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))
import _smoke  # noqa: F401,E402 — forces CPU under --smoke
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.batch_size, args.steps = 8, 2

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.parallel.mesh import make_mesh, shard_batch
    from mxnet_tpu.parallel.data_parallel import make_train_step

    mx.random.seed(0)
    n_dev = len(jax.devices())
    mesh = make_mesh({"dp": n_dev})
    print(f"devices: {n_dev}, mesh: {dict(mesh.shape)}")

    size = 32 if args.smoke else 64
    net = resnet18_v1()
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 3, size, size)))   # materialise deferred shapes

    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.create("sgd", learning_rate=0.05, momentum=0.9)
    step, init_state = make_train_step(net, loss, opt, mesh=mesh)
    state = init_state()

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (args.batch_size, 3, size, size))
    y = jax.random.randint(key, (args.batch_size,), 0, 10)
    xs, ys = shard_batch(mesh, x), shard_batch(mesh, y)

    for i in range(args.steps):
        state, l = step(state, xs, ys, 0.05, jax.random.PRNGKey(i))
        print(f"step {i}: loss={float(l):.4f}")


if __name__ == "__main__":
    main()
