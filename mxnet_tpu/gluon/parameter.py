"""Gluon Parameter / ParameterDict (reference: python/mxnet/gluon/parameter.py).

A Parameter owns one NDArray (weights live once, in HBM — data-parallel
replication is handled by sharded train steps, not per-device copies) plus an
optional gradient buffer. Deferred initialisation matches the reference: a
Parameter created with unknown dims (0) materialises at the first forward once
shapes are inferred.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import numpy as np

from ..base import MXNetError, _np_dtype
from ..context import Context, current_context
from .. import initializer as _initializer
from .. import random as _random
from ..ndarray.ndarray import NDArray

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when a deferred-init parameter's data is accessed before the
    first forward has inferred its shape."""


# Set by cachedop during its capture pre-pass (a collecting set): every
# Parameter whose CONCRETE data is read while tracing — i.e. one NOT
# overridden as a program input — is recorded here so the captured step
# can promote it to an input instead of baking its value into the
# executable as a compile-time constant (fine-tuning setups read frozen
# backbone params that are not in the Trainer's param list). None
# (default) keeps the hot path at one global load + is-None check.
_capture_watch = None


class Parameter:
    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = _np_dtype(dtype)
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._data = None            # NDArray
        self._grad = None            # NDArray
        self._deferred_init = None   # (initializer, ctx) awaiting shape
        self._trace_override = None  # traced value during hybridized tracing
        self._var = None             # symbol variable cache

    # ------------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._grad = None
                self._data._grad_req = "null"
            else:
                self._init_grad()

    def _shape_is_known(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        if self._data is not None and not force_reinit:
            return
        if init is None:
            init = self.init if self.init is not None else \
                (default_init if default_init is not None
                 else _initializer.Uniform(0.07))
        init = _initializer.create(init) if not isinstance(
            init, _initializer.Initializer) else init
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]  # weights live once; replication is via sharding
        if not self._shape_is_known():
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"Cannot initialize Parameter {self.name!r}: unknown "
                    f"shape {self.shape} and deferred init not allowed")
            self._deferred_init = (init, ctx)
            return
        self._finish_init(init, ctx)

    def _finish_init(self, init, ctx):
        key = _random._next_key()
        val = init(self.name, self.shape, self.dtype, key)
        self._data = NDArray(jax.device_put(val, Context(ctx).jax_device))
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        import jax.numpy as jnp
        self._grad = NDArray(jnp.zeros_like(self._data._data))
        self._data._grad = self._grad
        self._data._grad_req = self._grad_req

    def _finish_deferred_init(self, inferred_shape):
        """Called by layers at first forward once the full shape is known."""
        if self._deferred_init is None:
            return
        shape = tuple(inferred_shape)
        if self.shape is not None:
            merged = []
            for have, got in zip(self.shape, shape):
                if have > 0 and got > 0 and have != got:
                    raise MXNetError(
                        f"shape mismatch for {self.name}: declared "
                        f"{self.shape}, inferred {shape}")
                merged.append(have if have > 0 else got)
            shape = tuple(merged)
        self.shape = shape
        init, ctx = self._deferred_init
        self._finish_init(init, ctx)

    # ------------------------------------------------------------------
    def data(self, ctx=None):
        if self._trace_override is not None:
            return self._trace_override
        if _capture_watch is not None:
            _capture_watch.add(self)
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self.name!r} has deferred init; run a "
                    f"forward pass first")
            raise MXNetError(f"Parameter {self.name!r} has not been "
                             f"initialized. Call .initialize()")
        return self._data

    def list_data(self):
        return [self.data()]

    def grad(self, ctx=None):
        if self._grad is None:
            raise MXNetError(f"Parameter {self.name!r} has no gradient "
                             f"(grad_req={self._grad_req!r})")
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return [self._deferred_init[1]]
            return []
        return [self._data.context]

    def set_data(self, data):
        if not isinstance(data, NDArray):
            data = NDArray(jax.numpy.asarray(data))
        if self._data is None:
            self.shape = data.shape
            self._data = data.astype(self.dtype) if data.dtype != self.dtype else data
            self._deferred_init = None
            if self._grad_req != "null":
                self._init_grad()
        else:
            self._data._rebind(data._data.astype(self._data.dtype))
            if self._grad is not None:
                self._data._grad = self._grad
                self._data._grad_req = self._grad_req

    def zero_grad(self):
        if self._grad is not None:
            import jax.numpy as jnp
            self._grad._rebind(jnp.zeros_like(self._grad._data))

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data._rebind(jax.device_put(self._data._data,
                                              Context(ctx).jax_device))

    def cast(self, dtype):
        self.dtype = _np_dtype(dtype)
        if self._data is not None:
            self._data._rebind(self._data._data.astype(self.dtype))
            if self._grad is not None:
                self._grad._rebind(self._grad._data.astype(self.dtype))
                self._data._grad = self._grad

    def _struct_sig(self):
        """Structural identity consumed by the Trainer's fused-bucket
        cache: captures everything bucketing depends on (materialised
        shape/dtype, gradient dtype, grad_req), so deferred init, cast()
        and grad_req flips invalidate stale bucket layouts."""
        return (self.name,
                None if self._data is None
                else (tuple(self._data.shape), str(self._data.dtype)),
                None if self._grad is None else str(self._grad.dtype),
                self._grad_req)

    def var(self):
        from .. import symbol
        if self._var is None:
            self._var = symbol.var(self.name, shape=self.shape,
                                   dtype=self.dtype)
        return self._var

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"


class Constant(Parameter):
    """Non-differentiable parameter holding a fixed value."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = NDArray(jax.numpy.asarray(value))
        self.value = value
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype,
                         init=_initializer.Constant(0.0))
        self._data = value


class ParameterDict:
    """Ordered name->Parameter mapping with a shared prefix."""

    def __init__(self, prefix="", shared=None):
        self.prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key):
        return self._params[key]

    def __contains__(self, key):
        return key in self._params

    def __repr__(self):
        lines = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict({self.prefix}\n{lines}\n)"

    def get(self, name, **kwargs):
        """Retrieve or create a parameter with `self.prefix + name`."""
        full = self.prefix + name
        if full in self._params:
            return self._params[full]
        if self._shared is not None and full in self._shared:
            self._params[full] = self._shared[full]
            return self._params[full]
        param = Parameter(full, **kwargs)
        self._params[full] = param
        return param

    def get_constant(self, name, value=None):
        full = self.prefix + name
        if full not in self._params:
            self._params[full] = Constant(full, value)
        return self._params[full]

    def update(self, other):
        for k, v in other.items():
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self._params.values():
            p.initialize(init=None, ctx=ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        import numpy as _np
        arrays = {}
        for name, p in self._params.items():
            if p._data is None:
                continue
            key = name[len(strip_prefix):] if name.startswith(strip_prefix) else name
            arrays[key] = p.data().asnumpy()
        _np.savez(filename, **arrays)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        import numpy as _np
        with _np.load(filename) as f:
            loaded = {restore_prefix + k: f[k] for k in f.keys()}
        for name, p in self._params.items():
            if name in loaded:
                p.set_data(NDArray(jax.numpy.asarray(loaded[name])))
            elif not allow_missing:
                raise MXNetError(f"Parameter {name} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(self._params)
            if extra:
                raise MXNetError(f"extra parameters in file: {sorted(extra)}")
