"""mx.nd.contrib — control-flow operators (foreach / while_loop / cond).

Reference parity: python/mxnet/ndarray/contrib.py (imperative semantics) and
src/operator/control_flow.cc (the symbolic scan/while/cond operators).

TPU-native design: the reference has TWO implementations — an imperative one
(a plain Python loop over eager ops) and a symbolic one (nnvm subgraph ops
executed by the GraphExecutor). Here the split is by *trace context*:

- Called on concrete NDArrays (imperative), these run the reference's exact
  Python-loop semantics: every op inside the body dispatches eagerly and is
  recorded on the autograd tape per-op, so closures over parameters get
  gradients exactly as in the reference.
- Called on tracers — i.e. inside `jax.jit` via `HybridBlock.hybridize()`,
  `Symbol.bind`, or an exported pure fn — they lower to `lax.scan` /
  `lax.while_loop` / `lax.cond`: ONE compiled XLA While/Conditional op,
  which is the form the TPU wants (no Python unrolling, static shapes,
  fusion across the loop body).

Semantics notes (matching the reference):
- `foreach` iterates dim 0 of each data array; outputs are stacked on dim 0.
- `while_loop` imperative returns outputs with first dim = actual steps run;
  the traced/compiled path pads to `max_iterations` with zeros (the reference
  documents the same imperative/symbolic shape asymmetry).
- `cond` branch functions are thunks over closures, like the reference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, _as_list
from .ndarray import NDArray, _apply

__all__ = ["foreach", "while_loop", "cond",
           "interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt", "div_sqrt_dim",
           "arange_like", "index_copy", "index_array", "boolean_mask",
           "ROIAlign", "box_nms", "box_non_maximum_suppression", "box_iou",
           "box_encode", "box_decode", "MultiBoxPrior", "MultiBoxTarget",
           "MultiBoxDetection", "Proposal", "MultiProposal",
           "DeformableConvolution", "fft", "ifft", "count_sketch"]


def _is_traced(nds):
    return any(isinstance(x._data, jax.core.Tracer) for x in nds)


def _as_nd_list(x, what):
    xs = _as_list(x) if x is not None else []
    for v in xs:
        if not isinstance(v, NDArray):
            raise MXNetError(f"{what} must be NDArray(s), got {type(v)}")
    return list(xs)


def _pack_like(template, values):
    """Return values as a bare NDArray if the user passed one, else a list."""
    values = list(values)
    if not isinstance(template, (list, tuple)):
        return values[0] if len(values) == 1 else values
    return values


class _TracedBody:
    """Run a user body over raw jax values by round-tripping NDArray wrappers.

    Recording is suspended inside: under a trace the whole control-flow op is
    a single XLA op in an already-pure function, so the per-op tape must not
    see the tracer intermediates.
    """

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, *raw_groups):
        from .. import autograd
        prev = autograd.set_recording(False)
        try:
            nd_groups = [[NDArray(v) for v in grp] for grp in raw_groups]
            return self.fn(*nd_groups)
        finally:
            autograd.set_recording(prev)


def foreach(body, data, init_states):
    """Iterate `body` over dim 0 of `data`, threading `states` through.

    body(data_slice, states) -> (outputs, new_states). Outputs are stacked
    along a new leading axis; final states are returned alongside.

    Reference: python/mxnet/ndarray/contrib.py (foreach).
    """
    data_list = _as_nd_list(data, "foreach data")
    state_list = _as_nd_list(init_states, "foreach init_states")
    if not data_list:
        raise MXNetError("foreach needs at least one data array")
    length = data_list[0].shape[0]
    for d in data_list[1:]:
        if d.shape[0] != length:
            raise MXNetError("foreach data arrays must share dim 0 "
                             f"({d.shape[0]} != {length})")

    def call_body(slices, states):
        d_in = _pack_like(data, slices)
        s_in = _pack_like(init_states, states)
        outs, new_states = body(d_in, s_in)
        return _as_list(outs) if outs is not None else [], _as_list(new_states)

    if not _is_traced(data_list + state_list):
        # reference-exact imperative path: eager per-step ops on the tape
        states = state_list
        per_step = []
        for i in range(length):
            outs, states = call_body([d[i] for d in data_list], states)
            per_step.append(outs)
        return _stack_steps(per_step), _pack_like(init_states, states)

    # traced path: one lax.scan
    traced = _TracedBody(lambda d, s: call_body(d, s))

    def pure(*raw):
        nd_data = raw[:len(data_list)]
        nd_states = list(raw[len(data_list):])

        def step(carry, xs):
            outs, new_states = traced(list(xs), list(carry))
            return tuple(v._data for v in new_states), \
                tuple(v._data for v in outs)

        carry, ys = lax.scan(step, tuple(nd_states), tuple(nd_data))
        return tuple(ys) + tuple(carry)

    n_states = len(state_list)
    # probe output arity once (dead values; XLA removes them from the trace)
    from .. import autograd
    prev = autograd.set_recording(False)
    try:
        outs0, _ = call_body([d[0] for d in data_list], state_list)
    finally:
        autograd.set_recording(prev)
    n_out = len(outs0)
    res = _apply(pure, data_list + state_list, n_out=n_out + n_states)
    res = list(res) if isinstance(res, tuple) else [res]
    return (_pack_like_or_empty(res[:n_out]),
            _pack_like(init_states, res[n_out:]))


def _pack_like_or_empty(values):
    if not values:
        return []
    return values[0] if len(values) == 1 else values


def _stack_steps(per_step):
    """Stack the k-th output of every step along a new dim 0."""
    if not per_step or not per_step[0]:
        return []
    from ..ops.tensor_ops import stack
    return _pack_like_or_empty(
        [stack(*[step[k] for step in per_step], axis=0)
         for k in range(len(per_step[0]))])


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Run `func` while `cond` holds, up to `max_iterations`.

    cond(*loop_vars) -> scalar NDArray (truth value);
    func(*loop_vars) -> (step_output(s), new_loop_vars).
    Returns (outputs stacked on dim 0, final loop_vars). Imperative calls
    return the actual number of steps on dim 0; traced calls return
    `max_iterations` rows, zero-padded past termination (XLA static shapes).

    Reference: python/mxnet/ndarray/contrib.py (while_loop).
    """
    var_list = _as_nd_list(loop_vars, "while_loop loop_vars")
    if not var_list:
        raise MXNetError("while_loop needs at least one loop var")
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    max_iterations = int(max_iterations)

    def call_func(vs):
        outs, new_vars = func(*vs)
        return (_as_list(outs) if outs is not None else [],
                _as_list(new_vars))

    if not _is_traced(var_list):
        steps, vs = [], var_list
        for _ in range(max_iterations):
            keep = cond(*vs)
            if not bool(keep.asscalar() if isinstance(keep, NDArray) else keep):
                break
            outs, vs = call_func(vs)
            steps.append(outs)
        return _stack_steps(steps), _pack_like(loop_vars, vs)

    traced_cond = _TracedBody(lambda vs: cond(*vs))
    traced_func = _TracedBody(lambda vs: call_func(vs))

    from .. import autograd
    prev = autograd.set_recording(False)
    try:
        outs0, _ = call_func(var_list)
    finally:
        autograd.set_recording(prev)
    n_out, n_vars = len(outs0), len(var_list)

    def pure(*raw):
        init = tuple(raw)
        out_bufs = tuple(
            jnp.zeros((max_iterations,) + o.shape, o._data.dtype)
            for o in outs0)

        def step(carry, i):
            vs, bufs, active = carry
            keep = jnp.logical_and(
                active, jnp.squeeze(traced_cond(list(vs))._data).astype(bool))

            def take(args):
                vs, bufs = args
                outs, new_vars = traced_func(list(vs))
                new_bufs = tuple(
                    lax.dynamic_update_index_in_dim(b, o._data, i, 0)
                    for b, o in zip(bufs, outs))
                return tuple(v._data for v in new_vars), new_bufs

            new_vs, new_bufs = lax.cond(keep, take, lambda a: a, (vs, bufs))
            return (new_vs, new_bufs, keep), None

        (vs, bufs, _), _ = lax.scan(
            step, (init, out_bufs, jnp.bool_(True)),
            jnp.arange(max_iterations))
        return tuple(bufs) + tuple(vs)

    res = _apply(pure, var_list, n_out=n_out + n_vars)
    res = list(res) if isinstance(res, tuple) else [res]
    return (_pack_like_or_empty(res[:n_out]),
            _pack_like(loop_vars, res[n_out:]))


def cond(pred, then_func, else_func, inputs=None):
    """Select a branch on a scalar predicate.

    pred: scalar NDArray (or a thunk returning one); then/else are thunks
    over closures, like the reference's symbolic `cond`. Imperative calls
    evaluate only the taken branch; traced calls lower to `lax.cond` (both
    branches traced once, one selected at run time on device).

    Reference: python/mxnet/ndarray/contrib.py (cond).
    """
    if callable(pred):
        pred = pred()
    if not isinstance(pred, NDArray):
        raise MXNetError("cond pred must be a scalar NDArray")
    if inputs is not None:
        raise MXNetError("pass branch inputs via closures (reference API)")

    if not _is_traced([pred]):
        taken = then_func if bool(pred.asscalar()) else else_func
        outs = _as_list(taken())
        return outs[0] if len(outs) == 1 else outs

    # traced: both branches must produce matching pytrees
    def run_branch(fn):
        from .. import autograd
        prev = autograd.set_recording(False)
        try:
            return [o._data for o in _as_list(fn())]
        finally:
            autograd.set_recording(prev)

    raw = lax.cond(jnp.squeeze(pred._data).astype(bool),
                   lambda _: run_branch(then_func),
                   lambda _: run_branch(else_func), None)
    outs = [NDArray(r) for r in raw]
    return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# transformer/NLP helper ops (reference: src/operator/contrib/transformer.cc
# interleaved_matmul_selfatt_qk/valatt, div_sqrt_dim; tensor contrib
# arange_like, index_copy, index_array). The interleaved ops are the fused
# BERT self-attention entry points GluonNLP-era code calls; here each is a
# couple of einsums XLA fuses onto the MXU — the reference needed
# hand-written interleaved GEMMs to avoid transposes, the reshape/transpose
# below is free at trace time.
# ---------------------------------------------------------------------------
def _split_interleaved(qkv, heads):
    """(S, B, heads*3*dh) with per-head [q|k|v] packing ->
    three (B*heads, S, dh) arrays."""
    s, b, hd3 = qkv.shape
    dh = hd3 // (3 * heads)

    def pick(i):
        x = qkv.reshape(s, b, heads, 3, dh)[:, :, :, i, :]
        return x.transpose(1, 2, 0, 3).reshape(b * heads, s, dh)
    return pick(0), pick(1), pick(2), dh


def interleaved_matmul_selfatt_qk(queries_keys_values, heads, **kw):
    """(S, B, H*3*dh) -> (B*H, S, S) scaled q.k^T scores (the 1/sqrt(dh)
    scale is INSIDE the op, matching the reference kernel)."""
    def fn(qkv):
        q, k, _v, dh = _split_interleaved(qkv, heads)
        return jnp.einsum("nqd,nkd->nqk", q, k) / jnp.sqrt(
            jnp.asarray(dh, qkv.dtype))
    return _apply(fn, [queries_keys_values])


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads, **kw):
    """(S, B, H*3*dh) + (B*H, S, S) attention weights -> (S, B, H*dh)."""
    def fn(qkv, att):
        s, b, _ = qkv.shape
        _q, _k, v, dh = _split_interleaved(qkv, heads)
        out = jnp.einsum("nqk,nkd->nqd", att, v)       # (B*H, S, dh)
        return out.reshape(b, heads, s, dh).transpose(2, 0, 1, 3) \
                  .reshape(s, b, heads * dh)
    return _apply(fn, [queries_keys_values, attention])


def div_sqrt_dim(data, **kw):
    """data / sqrt(data.shape[-1]) (reference: contrib.div_sqrt_dim)."""
    return _apply(lambda x: x / jnp.sqrt(jnp.asarray(x.shape[-1],
                                                     x.dtype)), [data])


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None, **kw):
    """An arange shaped like `data` (flat) or like data's `axis` length
    (reference: contrib.arange_like — the shape comes from a tensor so the
    graph stays shape-polymorphic). With `repeat`, each value appears
    `repeat` times within the SAME total length (reference semantics:
    [0,0,1,1,...])."""
    def ramp(n, dtype):
        count = -(-n // repeat)  # ceil
        vals = start + step * jnp.arange(count, dtype=dtype)
        return jnp.repeat(vals, repeat)[:n]

    def fn(x):
        if axis is None:
            return ramp(x.size, x.dtype).reshape(x.shape)
        return ramp(x.shape[axis], x.dtype)
    return _apply(fn, [data])


def index_copy(old_tensor, index_vector, new_tensor, **kw):
    """Functional row copy: out = old with out[index[i]] = new[i]
    (reference: contrib.index_copy)."""
    def fn(old, idx, new):
        return old.at[idx.astype(jnp.int32)].set(new)
    return _apply(fn, [old_tensor, index_vector, new_tensor])


def boolean_mask(data, index, axis=0, **kw):
    """Rows of `data` where `index` is nonzero (reference:
    contrib.boolean_mask). Eager-only: the output length is
    data-dependent, which cannot live under jit (SURVEY §8 pattern —
    use nd.where/SequenceMask inside compiled code)."""
    import numpy as _onp
    mask = _onp.asarray(index._data).astype(bool)
    idx = _onp.nonzero(mask)[0]
    def fn(x, _i=jnp.asarray(idx, jnp.int32)):
        return jnp.take(x, _i, axis=axis)
    return _apply(fn, [data])


def index_array(data, axes=None, **kw):
    """Per-element coordinate array: out[i1..in] = (i1..in) (or the chosen
    axes), shape data.shape + (k,). int32, not the reference's int64 —
    JAX runs x64-disabled and index ranges fit (documented divergence)."""
    def fn(x):
        grids = jnp.meshgrid(*[jnp.arange(d) for d in x.shape],
                             indexing="ij")
        sel = grids if axes is None else [grids[a] for a in axes]
        return jnp.stack(sel, axis=-1).astype(jnp.int32)
    return _apply(fn, [data])


# ---------------------------------------------------------------------------
# detection / vision contrib ops (upstream: src/operator/contrib/
# roi_align.cc, bounding_box.cc, multibox_*.cc, proposal.cc,
# multi_proposal.cc, deformable_convolution.cc, fft.cc, count_sketch.cc).
# Kernels live in ops/detection_ops.py + ops/contrib_ops.py; these wrappers
# expose them under the reference nd.contrib names with reference layouts.
# ---------------------------------------------------------------------------
from ..ops import detection_ops as _det
from ..ops import contrib_ops as _cops


def ROIAlign(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
             sample_ratio=2, **kw):
    """data (B, C, H, W), rois (R, 5) [batch_idx, x0, y0, x1, y1] ->
    (R, C, ph, pw) (upstream: contrib.ROIAlign / roi_align.cc)."""
    pooled_size = tuple(pooled_size)
    return _apply(lambda d, r: _cops.roi_align_batched(
        d, r, pooled_size=pooled_size, spatial_scale=spatial_scale,
        sample_ratio=max(int(sample_ratio), 1)), [data, rois])


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, **kw):
    """NMS over rows of (..., N, K) box records; suppressed rows become -1
    (upstream: contrib.box_nms / bounding_box.cc)."""
    return _apply(lambda d: _cops.box_nms(
        d, overlap_thresh=overlap_thresh, valid_thresh=valid_thresh,
        topk=int(topk), coord_start=int(coord_start),
        score_index=int(score_index), id_index=int(id_index),
        background_id=int(background_id),
        force_suppress=bool(force_suppress)), [data])


box_non_maximum_suppression = box_nms


def box_iou(lhs, rhs, format="corner", **kw):
    """Pairwise IoU (upstream: contrib.box_iou): lhs (..., N, 4),
    rhs (..., M, 4) -> (..., N, M)."""
    return _apply(lambda a, b: _cops.box_iou_generic(a, b, format=format),
                  [lhs, rhs])


def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2), **kw):
    """GluonCV-style batched target encoding (upstream: contrib.box_encode):
    samples (B, A) {+1 pos, else ignore}, matches (B, A) gt indices,
    anchors (B, A, 4), refs (B, M, 4) -> (targets (B, A, 4), mask (B, A, 4)).
    Targets are (raw_offset - mean) / std, upstream's normalisation order.
    """
    def fn(s, m, a, r):
        def per(sb, mb, ab, rb):
            gt = rb[mb.astype(jnp.int32)]
            raw = _det.box_encode(gt, ab, variances=(1.0, 1.0, 1.0, 1.0))
            t = (raw - jnp.asarray(means, raw.dtype)) \
                / jnp.asarray(stds, raw.dtype)
            mask = (sb > 0.5)[:, None].astype(t.dtype)
            return t * mask, jnp.broadcast_to(mask, t.shape)
        return jax.vmap(per)(s, m, a, r)
    return _apply(fn, [samples, matches, anchors, refs], n_out=2)


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="corner", **kw):
    """Decode (B, A, 4) offsets against anchors (1|B, A, 4) (upstream:
    contrib.box_decode)."""
    def fn(d, a):
        a = _cops.to_corner(a, format)
        a2 = jnp.broadcast_to(a, d.shape)
        dec = jax.vmap(lambda dd, aa: _det.box_decode(
            dd, aa, variances=(std0, std1, std2, std3)))(d, a2)
        return jnp.clip(dec, 0.0, clip) if clip > 0 else dec
    return _apply(fn, [data, anchors])


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Anchors for a feature map (upstream: contrib.MultiBoxPrior):
    data (B, C, H, W) -> (1, H*W*K, 4) normalised corners."""
    return _apply(lambda d: _cops.multibox_prior_k(
        d, sizes=tuple(sizes), ratios=tuple(ratios), clip=bool(clip),
        offsets=tuple(offsets), steps=tuple(steps)), [data])


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """SSD target assignment (upstream: contrib.MultiBoxTarget).
    anchor (1, A, 4); label (B, M, 5) [cls x0 y0 x1 y1, cls=-1 pad];
    cls_pred (B, C+1, A) (shape source only). Returns the upstream triple
    [loc_target (B, A*4), loc_mask (B, A*4), cls_target (B, A)]."""
    return _apply(lambda a, lab, cp: _cops.multibox_target_k(
        a, lab, cp, overlap_threshold=overlap_threshold,
        variances=tuple(variances)), [anchor, label, cls_pred], n_out=3)


def MultiBoxDetection(cls_prob, loc_pred, anchor, threshold=0.01,
                      nms_threshold=0.45, nms_topk=400, max_det=100,
                      variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """Decode + per-class NMS (upstream: contrib.MultiBoxDetection).
    Output (B, max_det, 6) rows [cls_id, score, x0, y0, x1, y1], -1 pads —
    a STATIC detection budget instead of upstream's (B, A, 6) dynamic
    suppression (the XLA-friendly form; same surviving boxes)."""
    return _apply(lambda cp, lp, a: _cops.multibox_detection_k(
        cp, lp, a, threshold=threshold, nms_threshold=nms_threshold,
        nms_topk=int(nms_topk), max_det=int(max_det),
        variances=tuple(variances)), [cls_prob, loc_pred, anchor])


def MultiProposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                  rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                  scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                  feature_stride=16, output_score=False, **kw):
    """Batched RPN proposals (upstream: contrib.MultiProposal)."""
    def fn(cp, bp, info):
        rois, scores = _cops.multi_proposal(
            cp, bp, info, rpn_pre_nms_top_n=int(rpn_pre_nms_top_n),
            rpn_post_nms_top_n=int(rpn_post_nms_top_n),
            threshold=threshold, rpn_min_size=rpn_min_size,
            scales=tuple(scales), ratios=tuple(ratios),
            feature_stride=int(feature_stride))
        return (rois, scores) if output_score else rois
    return _apply(fn, [cls_prob, bbox_pred, im_info],
                  n_out=2 if output_score else 1)


def Proposal(cls_prob, bbox_pred, im_info, **kw):
    """Single-image RPN proposals (upstream: contrib.Proposal)."""
    if cls_prob.shape[0] != 1:
        raise MXNetError("Proposal expects batch 1; use MultiProposal")
    return MultiProposal(cls_prob, bbox_pred, im_info, **kw)


def DeformableConvolution(data, offset, weight, bias=None, kernel=(3, 3),
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=0, num_group=1, num_deformable_group=1,
                          no_bias=False, **kw):
    """Deformable conv v1 (upstream: contrib.DeformableConvolution).
    data (B, C, H, W); offset (B, 2*dg*kh*kw, OH, OW); weight
    (num_filter, C/num_group, kh, kw)."""
    def fn(*arrs):
        d, off, w = arrs[:3]
        b = arrs[3] if len(arrs) > 3 else None
        return _cops.deformable_convolution(
            d, off, w, bias=b, kernel=tuple(kernel), stride=tuple(stride),
            dilate=tuple(dilate), pad=tuple(pad), num_group=int(num_group),
            num_deformable_group=int(num_deformable_group))
    ins = [data, offset, weight]
    if bias is not None and not no_bias:
        ins.append(bias)
    return _apply(fn, ins)


def fft(data, compute_size=128, **kw):
    """Real -> interleaved [re, im] FFT along the last axis (upstream:
    contrib.fft; compute_size is a CUDA batching knob — accepted,
    irrelevant under XLA)."""
    return _apply(_cops.fft, [data])


def ifft(data, compute_size=128, **kw):
    """Interleaved [re, im] -> real inverse FFT, UNNORMALISED like the
    upstream kernel: ifft(fft(x)) == d * x."""
    return _apply(_cops.ifft, [data])


def count_sketch(data, h, s, out_dim, **kw):
    """Count-sketch projection to out_dim (upstream: contrib.count_sketch)."""
    return _apply(lambda d, hh, ss: _cops.count_sketch(
        d, hh, ss, int(out_dim)), [data, h, s])


# upstream documents these two under contrib (adaptive_avg_pooling.cc,
# bilinear_resize.cc); the implementations live with the other classic
# ops — re-export, don't duplicate
from ..ops.extra_ops import AdaptiveAvgPooling2D, BilinearResize2D  # noqa: E402,F401


# -- op-level quantization (reference: src/operator/quantization/*.cc) ------
def quantize(data, min_range, max_range, out_type="uint8", **kw):
    """float -> (q, out_min, out_max) inside the given tensor range
    (upstream: quantize.cc; uint8 affine, int8 symmetric)."""
    return _apply(lambda x, a, b: _cops.quantize(x, a, b, out_type),
                  [data, min_range, max_range], n_out=3)


def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None, **kw):
    """Calibrated (attr ranges) or dynamic (data min/max) quantization
    (upstream: quantize_v2.cc)."""
    return _apply(lambda x: _cops.quantize_v2(
        x, out_type, min_calib_range, max_calib_range), [data], n_out=3)


def dequantize(data, min_range, max_range, out_type="float32", **kw):
    """quantized (uint8/int8/int32) -> float32 (upstream: dequantize.cc)."""
    return _apply(lambda q, a, b: _cops.dequantize(q, a, b, out_type),
                  [data, min_range, max_range])


def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None, **kw):
    """int32 accumulator -> int8 with a new range (upstream:
    requantize.cc); returns (q8, out_min, out_max)."""
    return _apply(lambda q, a, b: _cops.requantize(
        q, a, b, min_calib_range, max_calib_range),
        [data, min_range, max_range], n_out=3)


def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, num_hidden=None,
                              no_bias=False, **kw):
    """int8 FC -> int32 accumulator (upstream:
    quantized_fully_connected.cc); (acc, out_min, out_max)."""
    ins = [data, weight] + ([] if no_bias or bias is None else [bias]) \
        + [min_data, max_data, min_weight, max_weight]

    def f(xq, wq, *rest):
        b, (mnd, mxd, mnw, mxw) = _cops.split_quantized_bias(rest)
        return _cops.quantized_fully_connected(
            xq, wq, b, mnd, mxd, mnw, mxw, num_hidden=num_hidden)
    return _apply(f, ins, n_out=3)


def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, kernel=None, stride=(1, 1), pad=(0, 0),
                   dilate=(1, 1), num_filter=None, layout="NCHW",
                   no_bias=False, **kw):
    """int8 conv -> int32 accumulator (upstream: quantized_conv.cc)."""
    ins = [data, weight] + ([] if no_bias or bias is None else [bias]) \
        + [min_data, max_data, min_weight, max_weight]

    def f(xq, wq, *rest):
        b, (mnd, mxd, mnw, mxw) = _cops.split_quantized_bias(rest)
        return _cops.quantized_conv(
            xq, wq, b, mnd, mxd, mnw, mxw, kernel=kernel, stride=stride,
            pad=pad, dilate=dilate, num_filter=num_filter, layout=layout)
    return _apply(f, ins, n_out=3)


def quantized_pooling(data, min_range, max_range, kernel=(2, 2),
                      pool_type="max", stride=None, pad=(0, 0),
                      layout="NCHW", **kw):
    """Pooling in the quantized domain (upstream: quantized_pooling.cc)."""
    return _apply(lambda q, a, b: _cops.quantized_pooling(
        q, a, b, kernel=kernel, pool_type=pool_type, stride=stride,
        pad=pad, layout=layout), [data, min_range, max_range], n_out=3)


def quantized_flatten(data, min_range, max_range, **kw):
    """reference: quantized_flatten.cc."""
    return _apply(_cops.quantized_flatten,
                  [data, min_range, max_range], n_out=3)
