"""Symbol-graph → ONNX exporter (reference: python/mxnet/contrib/onnx/
mx2onnx/export_model.py + _op_translations.py).

The reference converts nnvm graph JSON to ONNX via the `onnx` helper API;
that package is unavailable offline, so serialization goes through the
hand-rolled wire-format encoder in `proto.py`. The converter registry
mirrors the reference's per-op translation table. Target opset 11 (Dropout
ratio / Squeeze axes are still attributes there, Gemm's C is optional —
the most portable pre-13 opset).

Layout note: exported CNNs must be NCHW (ONNX's only layout) — the zoo
default. NHWC-built nets (the TPU fast path) should be re-built NCHW for
export; conversion is a deploy-time concern, not a train-time one.
"""
from __future__ import annotations

import math

import numpy as np

from ...base import MXNetError
from . import proto as P

__all__ = ["export_model"]

OPSET = 11
IR_VERSION = 6

_CONVERTERS = {}


def register_converter(opname):
    def deco(fn):
        _CONVERTERS[opname] = fn
        return fn
    return deco


class _Ctx:
    """Per-export state: tensor naming, emitted nodes, initializers."""

    def __init__(self):
        self.nodes = []          # encoded NodeProto bytes, topo order
        self.initializers = []   # encoded TensorProto bytes
        self.name_of = {}        # id(symbol node) -> output tensor name
        self.params = {}         # stripped name -> numpy array
        self.shape_of = {}       # tensor name -> inferred shape (or None)
        self.used = set()        # tensor names some node consumes
        self._uniq = 0

    def rank_of(self, tensor_name, default=4):
        s = self.shape_of.get(tensor_name)
        return len(s) if s is not None else default

    def channel_param(self, hint, array, data_rank):
        """A (C,)-param reshaped to broadcast against the channel axis of
        an NC... tensor of `data_rank` under ONNX's right-aligned rules:
        (C, 1, ..., 1) with data_rank-2 trailing ones."""
        arr = np.asarray(array, np.float32).reshape(
            (-1,) + (1,) * (data_rank - 2))
        return self.const(hint, arr)

    def tensor(self, sym_input):
        base, oi = sym_input._resolve_head()
        name = self.name_of[id(base)]
        return name if base._n_out == 1 else f"{name}.{oi}"

    def fresh(self, hint):
        self._uniq += 1
        return f"{hint}__{self._uniq}"

    def add_node(self, op_type, inputs, outputs, name, *attrs):
        self.used.update(inputs)
        self.nodes.append(P.message(
            *[P.f_bytes(1, i) for i in inputs],
            *[P.f_bytes(2, o) for o in outputs],
            P.f_bytes(3, name),
            P.f_bytes(4, op_type),
            *[P.f_bytes(5, a) for a in attrs]))

    def add_initializer(self, name, array):
        arr = np.ascontiguousarray(array)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        self.initializers.append(P.message(
            *[P.f_varint(1, d) for d in arr.shape],
            P.f_varint(2, P.onnx_dtype(arr.dtype)),
            P.f_bytes(8, name),
            P.f_bytes(9, arr.tobytes())))
        return name

    def const(self, hint, array):
        return self.add_initializer(self.fresh(hint), np.asarray(array))


# ------------------------------------------------------------ attr helpers
def A_f(name, v):
    return P.message(P.f_bytes(1, name), P.f_varint(20, P.ATTR_FLOAT),
                     P.f_float(2, v))


def A_i(name, v):
    return P.message(P.f_bytes(1, name), P.f_varint(20, P.ATTR_INT),
                     P.f_varint(3, v))


def A_s(name, v):
    return P.message(P.f_bytes(1, name), P.f_varint(20, P.ATTR_STRING),
                     P.f_bytes(4, v))


def A_ints(name, vs):
    return P.message(P.f_bytes(1, name), P.f_varint(20, P.ATTR_INTS),
                     *[P.f_varint(8, v) for v in vs])


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v), int(v))


# ------------------------------------------------------------- converters
@register_converter("Convolution")
def _conv(node, ctx, out):
    a = node._attrs
    k, s = _pair(a["kernel"]), _pair(a.get("stride", 1))
    p, d = _pair(a.get("pad", 0)), _pair(a.get("dilate", 1))
    if (a.get("layout") or "NCHW") != "NCHW":
        raise MXNetError("ONNX export requires NCHW convolutions; rebuild "
                         "the net with layout='NCHW' for export")
    ctx.add_node("Conv", [ctx.tensor(i) for i in node._inputs], [out],
                 node.name,
                 A_ints("kernel_shape", k), A_ints("strides", s),
                 A_ints("pads", (p[0], p[1], p[0], p[1])),
                 A_ints("dilations", d),
                 A_i("group", a.get("num_group", 1)))


@register_converter("StemConvS2D")
def _stem(node, ctx, out):
    # the space-to-depth stem is the NHWC TPU fast path: its weights are
    # pre-reshaped for the s2d input, so there is no attr-preserving ONNX
    # Conv equivalent — same story as the NHWC layout guard in _conv
    raise MXNetError(
        "ONNX export: StemConvS2D (stem_s2d=True, the NHWC TPU stem) has "
        "no ONNX equivalent; rebuild the net with stem_s2d=False / "
        "layout='NCHW' for export")


@register_converter("Deconvolution")
def _deconv(node, ctx, out):
    a = node._attrs
    if (a.get("layout") or "NCHW") != "NCHW":
        raise MXNetError("ONNX export requires NCHW deconvolutions")
    w = ctx.params.get(ctx.tensor(node._inputs[1]))
    k = tuple(w.shape[2:]) if w is not None else _pair(a["kernel"])
    s = _pair(a.get("stride", 1))
    p = _pair(a.get("pad", 0))
    adj = _pair(a.get("adj", 0))
    ctx.add_node("ConvTranspose", [ctx.tensor(i) for i in node._inputs],
                 [out], node.name,
                 A_ints("kernel_shape", k), A_ints("strides", s),
                 A_ints("pads", (p[0], p[1], p[0], p[1])),
                 A_ints("output_padding", adj),
                 A_i("group", 1))


@register_converter("InstanceNorm")
def _instancenorm(node, ctx, out):
    ctx.add_node("InstanceNormalization",
                 [ctx.tensor(i) for i in node._inputs], [out], node.name,
                 A_f("epsilon", node._attrs.get("eps", 1e-5)))


@register_converter("PReLU")
def _prelu(node, ctx, out):
    x = ctx.tensor(node._inputs[0])
    slope_name = ctx.tensor(node._inputs[1])
    alpha = ctx.params.get(slope_name)
    rank = ctx.rank_of(x)
    if alpha is not None and alpha.ndim == 1 and rank > 2:
        # ONNX PRelu broadcasts the slope from the RIGHT: a (C,) slope
        # must become (C, 1, ..., 1) to align with NC...'s channel axis
        # (rank-2 inputs broadcast (C,) directly)
        slope_name = ctx.channel_param(node.name + "_slope", alpha, rank)
    ctx.add_node("PRelu", [x, slope_name], [out], node.name)


@register_converter("GroupNorm")
def _groupnorm(node, ctx, out):
    # opset 11 has no GroupNormalization (opset 18): decompose via
    # Reshape(0, G, -1) -> normalize over axis 2 -> Reshape back to the
    # input's own Shape -> per-channel affine
    a = node._attrs
    g_count, eps = a.get("num_groups", 1), a.get("eps", 1e-5)
    x, gamma_n, beta_n = [ctx.tensor(i) for i in node._inputs]
    gamma = ctx.params.get(gamma_n)
    beta = ctx.params.get(beta_n)
    if gamma is None or beta is None:
        raise MXNetError(f"ONNX export: GroupNorm {node.name!r} needs "
                         "parameter gamma/beta")
    shp = ctx.const(node.name + "_gshape",
                    np.asarray([0, g_count, -1], np.int64))
    grouped = ctx.fresh(node.name + "_grouped")
    ctx.add_node("Reshape", [x, shp], [grouped], node.name + "_group")
    mu = ctx.fresh(node.name + "_mu")
    ctx.add_node("ReduceMean", [grouped], [mu], node.name + "_mu",
                 A_ints("axes", (2,)), A_i("keepdims", 1))
    xc = ctx.fresh(node.name + "_xc")
    ctx.add_node("Sub", [grouped, mu], [xc], node.name + "_sub")
    sq = ctx.fresh(node.name + "_sq")
    ctx.add_node("Mul", [xc, xc], [sq], node.name + "_sqm")
    var = ctx.fresh(node.name + "_var")
    ctx.add_node("ReduceMean", [sq], [var], node.name + "_varm",
                 A_ints("axes", (2,)), A_i("keepdims", 1))
    veps = ctx.fresh(node.name + "_veps")
    epsname = ctx.const(node.name + "_eps", np.float32(eps))
    ctx.add_node("Add", [var, epsname], [veps], node.name + "_adde")
    std = ctx.fresh(node.name + "_std")
    ctx.add_node("Sqrt", [veps], [std], node.name + "_sqrt")
    norm = ctx.fresh(node.name + "_norm")
    ctx.add_node("Div", [xc, std], [norm], node.name + "_div")
    xshape = ctx.fresh(node.name + "_xshape")
    ctx.add_node("Shape", [x], [xshape], node.name + "_shape")
    back = ctx.fresh(node.name + "_back")
    ctx.add_node("Reshape", [norm, xshape], [back], node.name + "_ungroup")
    rank = ctx.rank_of(x)
    gname = ctx.channel_param(node.name + "_gamma", gamma, rank)
    bname = ctx.channel_param(node.name + "_beta", beta, rank)
    scaled = ctx.fresh(node.name + "_scaled")
    ctx.add_node("Mul", [back, gname], [scaled], node.name + "_scale")
    ctx.add_node("Add", [scaled, bname], [out], node.name)


@register_converter("BatchNorm")
def _bn(node, ctx, out):
    a = node._attrs
    ins = [ctx.tensor(i) for i in node._inputs]
    if a.get("fix_gamma", True):
        # MXNet computes with gamma pinned to ones when fix_gamma (the sym
        # op's default); serializing raw gamma would silently diverge
        gamma = ctx.params.get(ins[1])
        if gamma is None:
            raise MXNetError(f"ONNX export: BatchNorm {node.name!r} has "
                             "fix_gamma=True but its gamma is not a "
                             "parameter; cannot pin to ones")
        ins[1] = ctx.const(node.name + "_fixed_gamma",
                           np.ones_like(np.asarray(gamma, np.float32)))
    ctx.add_node("BatchNormalization", ins, [out], node.name,
                 A_f("epsilon", a.get("eps", 1e-5)),
                 A_f("momentum", a.get("momentum", 0.9)))


@register_converter("Activation")
def _act(node, ctx, out):
    table = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus", "softsign": "Softsign"}
    act = node._attrs.get("act_type", "relu")
    if act == "gelu":
        return _gelu_tanh(node, ctx, out)
    if act not in table:
        raise MXNetError(f"ONNX export: unsupported act_type {act!r}")
    ctx.add_node(table[act], [ctx.tensor(node._inputs[0])], [out], node.name)


def _emit(ctx, nm, op, ins, hint, *attrs):
    """Emit one intermediate node `nm+hint` and return its output name —
    the shared helper for multi-node decomposition converters."""
    t = ctx.fresh(nm + hint)
    ctx.add_node(op, ins, [t], nm + hint, *attrs)
    return t


def _gelu_tanh(node, ctx, out):
    """gelu decomposed as the tanh approximation — the framework's eager
    kernel is jax.nn.gelu(approximate=True), so the export must emit the
    SAME curve: 0.5*x*(1 + tanh(sqrt(2/pi)*(x + 0.044715*x^3)))."""
    nm = node.name
    x = ctx.tensor(node._inputs[0])

    def n2(op, ins, hint, *attrs):
        return _emit(ctx, nm, op, ins, hint, *attrs)

    x2 = n2("Mul", [x, x], "_x2")
    x3 = n2("Mul", [x2, x], "_x3")
    c0 = ctx.const(nm + "_c0", np.float32(0.044715))
    inner = n2("Add", [x, n2("Mul", [x3, c0], "_cx3")], "_inner")
    cs = ctx.const(nm + "_s2pi", np.float32(math.sqrt(2.0 / math.pi)))
    th = n2("Tanh", [n2("Mul", [inner, cs], "_scaled")], "_tanh")
    one = ctx.const(nm + "_one", np.float32(1.0))
    half = ctx.const(nm + "_half", np.float32(0.5))
    gate = n2("Mul", [n2("Add", [th, one], "_1p"), half], "_gate")
    ctx.add_node("Mul", [x, gate], [out], nm)


@register_converter("Pooling")
def _pool(node, ctx, out):
    a = node._attrs
    x = ctx.tensor(node._inputs[0])
    ptype = a.get("pool_type", "max")
    if a.get("global_pool"):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        ctx.add_node(op, [x], [out], node.name)
        return
    k = _pair(a["kernel"])
    s = _pair(a.get("stride") or k)
    p = _pair(a.get("pad", 0))
    attrs = [A_ints("kernel_shape", k), A_ints("strides", s),
             A_ints("pads", (p[0], p[1], p[0], p[1]))]
    if ptype == "max":
        ctx.add_node("MaxPool", [x], [out], node.name, *attrs)
    else:
        attrs.append(A_i("count_include_pad",
                         1 if a.get("count_include_pad", True) else 0))
        ctx.add_node("AveragePool", [x], [out], node.name, *attrs)


@register_converter("FullyConnected")
def _fc(node, ctx, out):
    a = node._attrs
    x = ctx.tensor(node._inputs[0])
    w = ctx.tensor(node._inputs[1])
    b = None if a.get("no_bias") else ctx.tensor(node._inputs[2])
    if not a.get("flatten", True):
        # N-D input (e.g. (B,S,D) transformer activations): Gemm is 2-D
        # only in ONNX, so emit Transpose(W) + MatMul + Add instead
        wt = ctx.fresh(node.name + "_wT")
        ctx.add_node("Transpose", [w], [wt], node.name + "_wT",
                     A_ints("perm", (1, 0)))
        if b is None:
            ctx.add_node("MatMul", [x, wt], [out], node.name)
        else:
            mm = ctx.fresh(node.name + "_mm")
            ctx.add_node("MatMul", [x, wt], [mm], node.name + "_mm")
            ctx.add_node("Add", [mm, b], [out], node.name)
        return
    flat = ctx.fresh(node.name + "_flat")
    ctx.add_node("Flatten", [x], [flat], node.name + "_flatten",
                 A_i("axis", 1))
    ins = [flat, w] + ([b] if b is not None else [])
    ctx.add_node("Gemm", ins, [out], node.name,
                 A_f("alpha", 1.0), A_f("beta", 1.0),
                 A_i("transA", 0), A_i("transB", 1))


@register_converter("flatten")
def _flatten(node, ctx, out):
    ctx.add_node("Flatten", [ctx.tensor(node._inputs[0])], [out],
                 node.name, A_i("axis", 1))


def _softmax_decomposed(node, ctx, out, log):
    # opset-11 Softmax has coerce-to-2D semantics: only axis == last is
    # equivalent to MXNet's per-axis softmax, so other axes get the
    # explicit max-shifted Exp/ReduceSum/Div decomposition
    axis = node._attrs.get("axis", -1)
    x = ctx.tensor(node._inputs[0])
    mx_ = ctx.fresh(node.name + "_max")
    ctx.add_node("ReduceMax", [x], [mx_], node.name + "_max",
                 A_ints("axes", (axis,)), A_i("keepdims", 1))
    shifted = ctx.fresh(node.name + "_shift")
    ctx.add_node("Sub", [x, mx_], [shifted], node.name + "_shift")
    ex = ctx.fresh(node.name + "_exp")
    ctx.add_node("Exp", [shifted], [ex], node.name + "_exp")
    s = ctx.fresh(node.name + "_sum")
    ctx.add_node("ReduceSum", [ex], [s], node.name + "_sum",
                 A_ints("axes", (axis,)), A_i("keepdims", 1))
    if log:
        ls = ctx.fresh(node.name + "_logsum")
        ctx.add_node("Log", [s], [ls], node.name + "_logsum")
        ctx.add_node("Sub", [shifted, ls], [out], node.name)
    else:
        ctx.add_node("Div", [ex, s], [out], node.name)


def _masked_softmax(node, ctx, out, length, causal):
    """softmax(use_length=True and/or causal=True): mask the last axis by
    per-batch length and/or by the causal row bound, then softmax.
    Decomposed to Shape/Gather/Range/Less/And/Where so the sequence
    lengths stay DYNAMIC in the exported graph (any S at inference),
    mirroring the framework kernel's arange masks with the same -1e9
    fill. (opset 11 has no LessOrEqual, so causal col <= row emits
    Less(col, row + 1).)"""
    nm = node.name
    x = ctx.tensor(node._inputs[0])
    s = ctx.shape_of.get(x)
    if s is None:
        # the Unsqueeze axes below are rank-dependent; a guessed rank
        # would export a silently-wrong mask broadcast
        raise MXNetError(
            "ONNX export: masked softmax needs the data rank — "
            "pass input_shapes to export_model so shapes infer")
    rank = len(s)

    def n2(op, ins, hint, *attrs):
        return _emit(ctx, nm, op, ins, hint, *attrs)

    shape = n2("Shape", [x], "_shape")
    zero = ctx.const(nm + "_zero", np.asarray(0, np.int64))
    one = ctx.const(nm + "_one", np.asarray(1, np.int64))
    last = ctx.const(nm + "_lastidx", np.asarray(rank - 1, np.int64))
    sdim = n2("Gather", [shape, last], "_sdim", A_i("axis", 0))
    cols = n2("Range", [zero, sdim, one], "_range")         # (S,) int64
    mask = None
    if length:
        ln = ctx.tensor(node._inputs[1])
        lcast = n2("Cast", [ln], "_lcast", A_i("to", P.INT64))  # (B,)
        lexp = n2("Unsqueeze", [lcast], "_lexp",
                  A_ints("axes", tuple(range(1, rank))))    # (B,1,..,1)
        mask = n2("Less", [cols, lexp], "_lenmask")         # (B,1,..,S)
    if causal:
        rowidx = ctx.const(nm + "_rowidx", np.asarray(rank - 2, np.int64))
        qdim = n2("Gather", [shape, rowidx], "_qdim")
        rows = n2("Range", [zero, qdim, one], "_rowrange")  # (Sq,) int64
        rowsu = n2("Unsqueeze", [rows], "_rowsu", A_ints("axes", (1,)))
        rowp1 = n2("Add", [rowsu, one], "_rowp1")           # (Sq, 1)
        cmask = n2("Less", [cols, rowp1], "_causalmask")    # (Sq, S)
        mask = cmask if mask is None else \
            n2("And", [mask, cmask], "_mask")
    neg = ctx.const(nm + "_neg", np.float32(-1e9))
    masked = n2("Where", [mask, x, neg], "_masked")
    ctx.add_node("Softmax", [masked], [out], nm, A_i("axis", -1))


@register_converter("softmax")
def _softmax(node, ctx, out):
    axis = node._attrs.get("axis", -1)
    length = len(node._inputs) > 1
    causal = node._attrs.get("causal", False)
    if length or causal:
        if axis != -1:
            raise MXNetError("ONNX export: masked softmax is "
                             "last-axis only")
        return _masked_softmax(node, ctx, out, length, causal)
    if axis == -1:
        ctx.add_node("Softmax", [ctx.tensor(node._inputs[0])], [out],
                     node.name, A_i("axis", -1))
    else:
        _softmax_decomposed(node, ctx, out, log=False)


@register_converter("log_softmax")
def _log_softmax(node, ctx, out):
    axis = node._attrs.get("axis", -1)
    if axis == -1:
        ctx.add_node("LogSoftmax", [ctx.tensor(node._inputs[0])], [out],
                     node.name, A_i("axis", -1))
    else:
        _softmax_decomposed(node, ctx, out, log=True)


@register_converter("Dropout")
def _dropout(node, ctx, out):
    ctx.add_node("Dropout", [ctx.tensor(node._inputs[0])], [out],
                 node.name, A_f("ratio", node._attrs.get("p", 0.5)))


@register_converter("concat")
def _concat(node, ctx, out):
    ctx.add_node("Concat", [ctx.tensor(i) for i in node._inputs], [out],
                 node.name, A_i("axis", node._attrs.get("dim", 1)))


@register_converter("reshape")
def _reshape(node, ctx, out):
    shape = ctx.const(node.name + "_shape",
                      np.asarray(node._attrs["shape"], dtype=np.int64))
    ctx.add_node("Reshape", [ctx.tensor(node._inputs[0]), shape], [out],
                 node.name)


@register_converter("slice_axis")
def _slice_axis(node, ctx, out):
    a = node._attrs
    end = a.get("end")
    ends = np.asarray([2**62 if end is None else end], np.int64)
    ins = [ctx.tensor(node._inputs[0]),
           ctx.const(node.name + "_starts",
                     np.asarray([a["begin"]], np.int64)),
           ctx.const(node.name + "_ends", ends),
           ctx.const(node.name + "_axes",
                     np.asarray([a["axis"]], np.int64))]
    ctx.add_node("Slice", ins, [out], node.name)


@register_converter("transpose")
def _transpose(node, ctx, out):
    axes = node._attrs.get("axes")
    attrs = [A_ints("perm", axes)] if axes else []
    ctx.add_node("Transpose", [ctx.tensor(node._inputs[0])], [out],
                 node.name, *attrs)


@register_converter("expand_dims")
def _expand_dims(node, ctx, out):
    ctx.add_node("Unsqueeze", [ctx.tensor(node._inputs[0])], [out],
                 node.name, A_ints("axes", (node._attrs["axis"],)))


@register_converter("squeeze")
def _squeeze(node, ctx, out):
    ax = node._attrs.get("axis")
    if ax is None:
        attrs = []
    else:
        axes = tuple(ax) if isinstance(ax, (tuple, list)) else (ax,)
        attrs = [A_ints("axes", axes)]
    ctx.add_node("Squeeze", [ctx.tensor(node._inputs[0])], [out],
                 node.name, *attrs)


@register_converter("Embedding")
def _embedding(node, ctx, out):
    idx = ctx.fresh(node.name + "_idx")
    ctx.add_node("Cast", [ctx.tensor(node._inputs[0])], [idx],
                 node.name + "_cast", A_i("to", P.INT64))
    ctx.add_node("Gather", [ctx.tensor(node._inputs[1]), idx], [out],
                 node.name, A_i("axis", 0))


@register_converter("LayerNorm")
def _layernorm(node, ctx, out):
    # opset 11 has no LayerNormalization (added in 17): emit the primitive
    # decomposition mean/var normalize + affine, matching numerics
    a = node._attrs
    axis, eps = a.get("axis", -1), a.get("eps", 1e-5)
    x, g, b = [ctx.tensor(i) for i in node._inputs]
    mu = ctx.fresh(node.name + "_mean")
    ctx.add_node("ReduceMean", [x], [mu], node.name + "_mu",
                 A_ints("axes", (axis,)), A_i("keepdims", 1))
    xc = ctx.fresh(node.name + "_centered")
    ctx.add_node("Sub", [x, mu], [xc], node.name + "_sub")
    sq = ctx.fresh(node.name + "_sq")
    ctx.add_node("Mul", [xc, xc], [sq], node.name + "_sq_mul")
    var = ctx.fresh(node.name + "_var")
    ctx.add_node("ReduceMean", [sq], [var], node.name + "_varm",
                 A_ints("axes", (axis,)), A_i("keepdims", 1))
    veps = ctx.fresh(node.name + "_vareps")
    epsname = ctx.const(node.name + "_eps", np.float32(eps))
    ctx.add_node("Add", [var, epsname], [veps], node.name + "_addeps")
    std = ctx.fresh(node.name + "_std")
    ctx.add_node("Sqrt", [veps], [std], node.name + "_sqrt")
    norm = ctx.fresh(node.name + "_norm")
    ctx.add_node("Div", [xc, std], [norm], node.name + "_div")
    scaled = ctx.fresh(node.name + "_scaled")
    ctx.add_node("Mul", [norm, g], [scaled], node.name + "_scale")
    ctx.add_node("Add", [scaled, b], [out], node.name)


def _binary(onnx_op):
    def conv(node, ctx, out):
        ctx.add_node(onnx_op, [ctx.tensor(i) for i in node._inputs], [out],
                     node.name)
    return conv


for _mx, _ox in [("elemwise_add", "Add"), ("elemwise_sub", "Sub"),
                 ("elemwise_mul", "Mul"), ("elemwise_div", "Div"),
                 ("broadcast_add", "Add"), ("broadcast_sub", "Sub"),
                 ("broadcast_mul", "Mul"), ("broadcast_div", "Div"),
                 ("dot", "MatMul"), ("batch_dot", "MatMul")]:
    _CONVERTERS[_mx] = _binary(_ox)


def _scalar(onnx_op, swap=False):
    def conv(node, ctx, out):
        c = ctx.const(node.name + "_scalar",
                      np.float32(node._attrs["scalar"]))
        x = ctx.tensor(node._inputs[0])
        ins = [c, x] if swap else [x, c]
        ctx.add_node(onnx_op, ins, [out], node.name)
    return conv


for _mx, _ox, _swap in [("elemwise_add_scalar", "Add", False),
                        ("elemwise_sub_scalar", "Sub", False),
                        ("elemwise_mul_scalar", "Mul", False),
                        ("elemwise_div_scalar", "Div", False),
                        ("rsub_scalar", "Sub", True),
                        ("rdiv_scalar", "Div", True)]:
    _CONVERTERS[_mx] = _scalar(_ox, _swap)


def _unary(onnx_op):
    def conv(node, ctx, out):
        ctx.add_node(onnx_op, [ctx.tensor(node._inputs[0])], [out],
                     node.name)
    return conv


for _mx, _ox in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                 ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                 ("sqrt", "Sqrt"), ("negative", "Neg"), ("abs", "Abs"),
                 ("square", None)]:
    if _ox:
        _CONVERTERS[_mx] = _unary(_ox)


@register_converter("square")
def _square(node, ctx, out):
    x = ctx.tensor(node._inputs[0])
    ctx.add_node("Mul", [x, x], [out], node.name)


# ------------------------------------------------------------- entry point
def _strip(params):
    """Accept reference-style 'arg:x'/'aux:x' keys or plain names; values
    may be NDArray or numpy."""
    out = {}
    for k, v in params.items():
        name = k.split(":", 1)[1] if k.startswith(("arg:", "aux:")) else k
        out[name] = v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)
    return out


def _value_info(name, shape, elem_type=P.FLOAT):
    # a PRESENT-but-empty TensorShapeProto means rank 0 in ONNX; unknown
    # shape must OMIT the shape field entirely (unknown rank)
    parts = [P.f_varint(1, elem_type)]
    if shape:
        dims = P.message(*[P.f_bytes(1, P.message(P.f_varint(1, d)))
                           for d in shape])
        parts.append(P.f_bytes(2, dims))
    tensor = P.message(*parts)
    return P.message(P.f_bytes(1, name),
                     P.f_bytes(2, P.message(P.f_bytes(1, tensor))))


def export_model(sym, params, input_shapes=None, in_dtype="float32",
                 onnx_file_path="model.onnx", graph_name="mxnet_tpu"):
    """Export a Symbol + params to an ONNX file (reference:
    mx.contrib.onnx.export_model). `input_shapes` maps data-variable names
    to shapes (or a single tuple when there is one input); shapes are only
    metadata in the file, so dynamic batch still works downstream.
    Returns the path written."""
    params = _strip(params)
    nodes = sym._topo()
    heads = sym._head_entries()
    ctx = _Ctx()
    ctx.params = params

    data_inputs = []
    if isinstance(input_shapes, (tuple, list)) and input_shapes and \
            not isinstance(input_shapes[0], (tuple, list, dict)):
        input_shapes = {"data": tuple(input_shapes)}
    input_shapes = dict(input_shapes or {})

    # per-tensor shape table (rank-dependent converters: PReLU/GroupNorm
    # channel-param broadcasting): one inference pass over the internals
    if input_shapes:
        try:
            from ...symbol.symbol import Group as _Group, _node_output
            internals = _Group([_node_output(n, i) for n in nodes
                                for i in range(n._n_out)])
            _, int_shapes, _ = internals.infer_shape(**input_shapes)
            if int_shapes is not None:
                k = 0
                for n in nodes:
                    for i in range(n._n_out):
                        name = n.name if n._n_out == 1 else f"{n.name}.{i}"
                        ctx.shape_of[name] = int_shapes[k]
                        k += 1
        except Exception:
            pass  # shapes stay unknown; converters use their defaults

    param_vars = []
    for n in nodes:
        if n._op is None:
            ctx.name_of[id(n)] = n.name
            if n.name in params:
                param_vars.append(n.name)
            else:
                shape = input_shapes.get(n.name, n._shape_hint or ())
                data_inputs.append(_value_info(
                    n.name, shape, P.onnx_dtype(np.dtype(in_dtype))))
            continue
        conv = _CONVERTERS.get(n._op)
        if conv is None:
            raise MXNetError(
                f"ONNX export: no converter for op {n._op!r} "
                f"(node {n.name!r}); supported: "
                f"{sorted(_CONVERTERS)}")
        ctx.name_of[id(n)] = n.name
        conv(n, ctx, n.name)

    # serialize only params some emitted node consumes: converters that
    # substitute reshaped copies (PReLU slope, GroupNorm affine, fixed
    # gamma) would otherwise leave dead duplicates in the file
    for name in param_vars:
        if name in ctx.used:
            ctx.add_initializer(name, params[name])

    out_infos = []
    for hn, oi in heads:
        name = ctx.name_of[id(hn)]
        if hn._n_out > 1:
            name = f"{name}.{oi}"
        out_infos.append(_value_info(name, ()))

    graph = P.message(
        *[P.f_bytes(1, n) for n in ctx.nodes],
        P.f_bytes(2, graph_name),
        *[P.f_bytes(5, t) for t in ctx.initializers],
        *[P.f_bytes(11, v) for v in data_inputs],
        *[P.f_bytes(12, v) for v in out_infos])
    model = P.message(
        P.f_varint(1, IR_VERSION),
        P.f_bytes(2, "mxnet_tpu"),
        P.f_bytes(3, "1.0"),
        P.f_bytes(7, graph),
        P.f_bytes(8, P.message(P.f_bytes(1, ""), P.f_varint(2, OPSET))))
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    return onnx_file_path
