"""Expert-parallelism bench: `ShardedMoE` token routing vs the
equal-parameter dense FFN it sparsifies (ISSUE 16; docs/PERFORMANCE.md
"Expert parallelism").

Two arms on the same captured-step protocol, stem + feed-forward block:

  * moe — a `ShardedMoE(units, hidden, E, k)` layer, expert banks
    row-sharded over 'tp' on the (2,2) ('dp','tp') DEFAULT_RULES mesh:
    the captured step lowers dispatch/combine to exactly 2 all-to-alls
    per layer per traversal (`moe_step`), each device computing E/tp
    expert FFNs over its routed token slots;
  * dense — the same stem with one dense FFN of hidden = E * hidden:
    the SAME parameter count (the quality budget), but every token
    pays the full E*hidden FLOPs instead of k*hidden. This is the
    layer MoE sparsifies (Switch arXiv:2101.03961).

The headline is `moe_step_throughput` with the `moe_vs_dense_ffn`
ratio; `moe_drop_frac` reports the capacity-overflow fraction the run
actually suffered (the loud-accounting contract: at
capacity_factor=1.25 it should sit well under 0.05 — a warning prints
if it doesn't) and `moe_a2a_bytes_per_step` prices the routing wire
traffic from the `kv_collective_bytes{op=moe_all_to_all}` counter.

Needs >= 4 devices (the (2,2) mesh); below that `value: None` so the
bench.py supervisor fields are omitted honestly rather than faked —
the BENCH_SHARD=0 pattern.

Standalone: `python bench_moe.py` prints ONE JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

# per-chip samples/s denominator for vs_baseline: a routing step this
# size is all-to-all/latency-bound on the CPU mesh, not compute-bound;
# same spirit as bench_rec's denominator
BASELINE_SAMPLES_S = 100_000.0

UNITS, HIDDEN, EXPERTS, TOP_K, CAP_FACTOR = 32, 64, 8, 2, 1.25


def _setup():
    """(batch, steps, input batches, labels). Batch divisible by the
    (2,2) mesh's 4 token shards."""
    import jax
    import numpy as np

    on_tpu = jax.default_backend() == "tpu"
    batch = 256 if on_tpu else 32
    steps = 30 if on_tpu else 4

    rng = np.random.RandomState(0)
    Xb = rng.randn(8, batch, UNITS).astype(np.float32)
    yb = rng.randn(8, batch, UNITS).astype(np.float32)
    return batch, steps, Xb, yb


def _build(moe):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    class _Net(gluon.nn.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.stem = gluon.nn.Dense(UNITS, in_units=UNITS)
                if moe:
                    self.ffn = gluon.nn.ShardedMoE(
                        UNITS, HIDDEN, num_experts=EXPERTS, k=TOP_K,
                        capacity_factor=CAP_FACTOR)
                else:
                    # equal-parameter dense twin: E experts of `hidden`
                    # collapse into ONE (units -> E*hidden -> units) FFN
                    self.up = gluon.nn.Dense(EXPERTS * HIDDEN,
                                             activation="relu",
                                             in_units=UNITS)
                    self.down = gluon.nn.Dense(UNITS,
                                               in_units=EXPERTS * HIDDEN)

        def hybrid_forward(self, F_, x):
            h = self.stem(x)
            if moe:
                return self.ffn(h)
            return x + self.down(self.up(h))     # residual, like the MoE

    mx.random.seed(0)
    net = _Net()
    net.initialize(mx.init.Xavier())
    return net


def measure(on_result=None):
    """The supervisor arm: sharded-MoE vs equal-parameter dense-FFN
    captured steps. Returns the `moe_*` contract fields; `value: None`
    below 4 devices."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.observability import registry

    if len(jax.devices()) < 4:
        res = {"metric": "moe_step_throughput", "value": None,
               "unit": "samples/sec/chip",
               "skipped": "needs >= 4 devices"}
        print("[bench_moe] skipped (needs >= 4 devices)",
              file=sys.stderr)
        if on_result is not None:
            on_result(res)
        return res

    batch, steps, Xb, yb = _setup()
    lossf = gluon.loss.L2Loss()
    a2a = registry().counter("kv_collective_bytes", op="moe_all_to_all")

    def run(moe):
        net = _build(moe)
        net(nd.array(Xb[0]))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore="ici")
        tr.shard(mesh={"dp": 2, "tp": 2})
        step = tr.capture(lambda x, y: lossf(net(x), y).mean())

        for k in range(2):
            step(nd.array(Xb[k]), nd.array(yb[k]))   # compile + warm
        fallback = step.last_fallback_reason
        t0 = time.monotonic()
        for k in range(steps):
            L = step(nd.array(Xb[k % 8]), nd.array(yb[k % 8]))
        float(L.asnumpy())
        dt = time.monotonic() - t0

        drop_frac = None
        if moe:
            stats = net.ffn.publish_metrics()
            drop_frac = float(stats["overflow_frac"])
        return steps / dt, drop_frac, fallback

    a2a0 = a2a.value
    moe_steps_s, drop_frac, moe_fb = run(True)
    a2a_bytes = a2a.value - a2a0
    dense_steps_s, _, dense_fb = run(False)
    if moe_fb is not None:
        print(f"[bench_moe] WARNING: moe arm fell back ({moe_fb}); "
              f"the ratio measures the imperative path", file=sys.stderr)
    if drop_frac is not None and drop_frac >= 0.05:
        print(f"[bench_moe] WARNING: overflow fraction {drop_frac:.4f} "
              f">= 0.05 at capacity_factor={CAP_FACTOR} — routing is "
              f"dropping too many tokens for this gate/data",
              file=sys.stderr)

    res = {
        "metric": "moe_step_throughput",
        "value": round(moe_steps_s * batch / 4, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(moe_steps_s * batch / 4
                             / BASELINE_SAMPLES_S, 4),
        "mesh": {"dp": 2, "tp": 2},
        "moe_config": {"units": UNITS, "hidden": HIDDEN,
                       "experts": EXPERTS, "k": TOP_K,
                       "capacity_factor": CAP_FACTOR},
        "moe_steps_s": round(moe_steps_s, 3),
        "dense_ffn_steps_s": round(dense_steps_s, 3),
        "moe_vs_dense_ffn": round(moe_steps_s / dense_steps_s, 3),
        "moe_drop_frac": (None if drop_frac is None
                          else round(drop_frac, 4)),
        "moe_a2a_bytes_per_step": (None if a2a_bytes == 0
                                   else int(a2a_bytes // (steps + 2))),
        "fallback": moe_fb,
        "dense_fallback": dense_fb,
    }
    print(f"[bench_moe] moe {moe_steps_s:.2f} steps/s vs "
          f"{dense_steps_s:.2f} dense FFN "
          f"({res['moe_vs_dense_ffn']}x); drop frac "
          f"{res['moe_drop_frac']}; "
          f"{res['moe_a2a_bytes_per_step']} all-to-all B/step",
          file=sys.stderr)
    if on_result is not None:
        on_result(res)
    return res


def main():
    # fork CPU devices BEFORE jax imports so the (2,2) mesh exists on a
    # laptop/CI run (no-op when jax is already in, e.g. under bench.py)
    if "jax" not in sys.modules \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu" \
            and "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_"
                                     "device_count=4")
    res = measure()
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
