"""mx.contrib.text + the round-5 contrib submodules (reference:
python/mxnet/contrib/{text,io,autograd,tensorboard}.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.contrib import text


def test_count_tokens_and_vocabulary():
    c = text.utils.count_tokens_from_str("a b b c c c\nd a",
                                         to_lower=True)
    assert c["c"] == 3 and c["a"] == 2
    v = text.vocab.Vocabulary(c, min_freq=2,
                              reserved_tokens=["<pad>"])
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    # frequency rank then alpha; min_freq drops d (freq 1)
    assert "d" not in v.token_to_idx and "b" in v.token_to_idx
    assert v.to_indices("zzz") == 0
    assert v.to_tokens(0) == "<unk>"
    with pytest.raises(mx.base.MXNetError):
        v.to_tokens(len(v))
    with pytest.raises(mx.base.MXNetError):
        text.vocab.Vocabulary(c, unknown_token="<pad>",
                              reserved_tokens=["<pad>"])


def test_custom_and_composite_embedding(tmp_path):
    p = tmp_path / "emb.txt"
    p.write_text("a 1.0 2.0\nb 3.0 4.0\nc 5.0 6.0\n")
    emb = text.embedding.CustomEmbedding(str(p))
    assert emb.vec_len == 2 and len(emb) == 4   # <unk> + 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["b", "nope"]).asnumpy(),
        [[3, 4], [0, 0]])
    emb.update_token_vectors("a", nd.array(np.array([9.0, 9.0],
                                                    np.float32)))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [9, 9])
    # restricted onto an explicit vocabulary
    import collections
    v = text.vocab.Vocabulary(collections.Counter(
        {"a": 2, "b": 2, "x": 2}))
    emb2 = text.embedding.CustomEmbedding(str(p), vocabulary=v)
    assert len(emb2) == len(v)
    np.testing.assert_allclose(
        emb2.get_vecs_by_tokens("x").asnumpy(), [0, 0])
    comp = text.embedding.CompositeEmbedding(v, [emb2, emb2])
    assert comp.idx_to_vec.shape == (len(v), 4)
    with pytest.raises(mx.base.MXNetError):
        text.embedding.GloVe()
    # corrupt rows raise with the file:line
    bad = tmp_path / "bad.txt"
    bad.write_text("a 1.0 2.0\nb 3.0 oops\n")
    with pytest.raises(mx.base.MXNetError):
        text.embedding.CustomEmbedding(str(bad))


def test_dataloader_iter_adapts_to_module():
    rs = np.random.RandomState(0)
    X = rs.randn(64, 4).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.float32)
    ds = gluon.data.ArrayDataset(nd.array(X), nd.array(y))
    it = mx.contrib.io.DataLoaderIter(
        gluon.data.DataLoader(ds, batch_size=16))
    assert it.provide_data[0].shape == (16, 4)
    from mxnet_tpu.module import Module
    from mxnet_tpu import sym
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                           name="fc"),
        sym.Variable("softmax_label"), name="softmax")
    mod = Module(net, data_names=["data"],
                 label_names=["softmax_label"])
    mod.fit(it, num_epoch=8, optimizer="adam",
            optimizer_params={"learning_rate": 0.05})
    it.reset()
    m = mx.metric.Accuracy()
    mod.score(it, m)
    assert m.get()[1] > 0.9, m.get()


def test_contrib_autograd_legacy_api():
    from mxnet_tpu.contrib import autograd as cag
    x = nd.array(np.array([2.0, 3.0], np.float32))
    grads, loss = cag.grad_and_loss(lambda a: (a * a).sum())(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [4.0, 6.0])
    assert float(loss.asnumpy()) == 13.0
    with cag.train_section():
        pass                      # alias of autograd.record


def test_tensorboard_callback(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    cb = mx.contrib.tensorboard.LogMetricsCallback(str(tmp_path),
                                                   prefix="val")
    m = mx.metric.Accuracy()
    m.update([nd.array([0, 1])], [nd.array([[0.9, 0.1], [0.2, 0.8]])])
    from mxnet_tpu.callback import BatchEndParam
    cb(BatchEndParam(epoch=0, nbatch=0, eval_metric=m, locals=None))
    cb.summary_writer.flush()
    assert any(os.listdir(tmp_path))


def test_contrib_op_namespace_aliases():
    assert mx.contrib.ndarray is mx.nd.contrib
    assert mx.contrib.symbol is mx.sym.contrib


def test_text_delimiter_and_det_std_guards():
    """review r5: multi-char delimiters split whole tokens (upstream
    alternation semantics); CreateDetAugmenter treats std=False like
    CreateAugmenter does (no divide-by-zero normalize stage)."""
    c = text.utils.count_tokens_from_str("hello<sep>world",
                                         token_delim="<sep>")
    assert c == {"hello": 1, "world": 1}
    img = nd.array(np.full((4, 4, 3), 100.0, np.float32))
    augs = mx.image.CreateDetAugmenter((3, 4, 4), mean=True, std=False)
    out, lab = img, np.full((1, 5), -1.0, np.float32)
    for a in augs:
        out, lab = a(out, lab) if isinstance(a, mx.image.DetAugmenter) \
            else (a(out), lab)
    assert np.isfinite(np.asarray(out.asnumpy())).all()
