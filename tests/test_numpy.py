"""mx.np / mx.npx numpy front end (SURVEY.md §2 row 58; reference:
python/mxnet/numpy/ + numpy_extension/). The design under test: np-ness
propagates through the single `_apply` dispatch point, so one rule covers
ops, Gluon blocks and autograd."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
np = mx.np
npx = mx.npx


# ----------------------------------------------------------------- creation
def test_creation_and_repr():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, np.ndarray) and isinstance(a, nd.NDArray)
    assert "array(" in repr(a)
    assert np.zeros((2, 3)).shape == (2, 3)
    assert np.ones(4, dtype="int32").dtype == onp.int32
    assert np.arange(5).asnumpy().tolist() == [0, 1, 2, 3, 4]
    onp.testing.assert_allclose(np.linspace(0, 1, 5).asnumpy(),
                                onp.linspace(0, 1, 5), rtol=1e-6)
    assert np.eye(3).asnumpy()[1, 1] == 1.0
    assert np.full((2,), 7.0).asnumpy().tolist() == [7.0, 7.0]


def test_zero_dim_and_scalars():
    s = np.array(3.5)
    assert s.shape == () and s.ndim == 0
    assert s.item() == pytest.approx(3.5)
    total = np.sum(np.ones((3, 3)))
    assert total.shape == ()          # numpy semantics: 0-d, not (1,)
    assert float(total) == 9.0


def test_type_propagation_through_nd_ops():
    """Any op touching an np input returns np — including classic nd ops."""
    a = np.ones((2, 3))
    b = nd.ones((2, 3))
    assert isinstance(a + b, np.ndarray)
    assert isinstance(b + a, np.ndarray)       # nd op, np operand
    assert isinstance(nd.concat(b, b, dim=0), nd.NDArray)
    assert not isinstance(nd.concat(b, b, dim=0), np.ndarray)
    assert isinstance(a.as_nd_ndarray(), nd.NDArray)
    assert not isinstance(a.as_nd_ndarray(), np.ndarray)
    assert isinstance(b.as_np_ndarray(), np.ndarray)


# ---------------------------------------------------------------- arithmetic
def test_arithmetic_matches_numpy():
    x = onp.random.RandomState(0).randn(3, 4).astype(onp.float32)
    y = onp.random.RandomState(1).randn(4).astype(onp.float32)
    a, b = np.array(x), np.array(y)
    onp.testing.assert_allclose((a + b).asnumpy(), x + y, rtol=1e-6)
    onp.testing.assert_allclose((a * 2 - b / 3).asnumpy(), x * 2 - y / 3,
                                rtol=1e-5)
    onp.testing.assert_allclose((a @ b).asnumpy(), x @ y, rtol=1e-5)
    onp.testing.assert_allclose(np.maximum(a, 0).asnumpy(),
                                onp.maximum(x, 0))
    onp.testing.assert_allclose(np.exp(a).asnumpy(), onp.exp(x), rtol=1e-5)
    onp.testing.assert_allclose(np.hypot(a, a).asnumpy(), onp.hypot(x, x),
                                rtol=1e-6)
    assert (np.equal(a, a).asnumpy()).all()
    assert np.logical_not(np.zeros(3)).asnumpy().all()


def test_reductions_match_numpy():
    x = onp.random.RandomState(2).rand(4, 5).astype(onp.float32)
    a = np.array(x)
    onp.testing.assert_allclose(np.mean(a, axis=0).asnumpy(), x.mean(0),
                                rtol=1e-5)
    onp.testing.assert_allclose(np.var(a, ddof=1).item(), x.var(ddof=1),
                                rtol=1e-4)
    onp.testing.assert_allclose(np.cumsum(a, axis=1).asnumpy(),
                                x.cumsum(1), rtol=1e-5)
    assert np.argmax(a).item() == x.argmax()
    onp.testing.assert_allclose(np.median(a).item(), onp.median(x),
                                rtol=1e-5)
    assert a.std(axis=1).shape == (4,)


# ------------------------------------------------------------------ indexing
def test_boolean_and_fancy_indexing():
    x = onp.arange(12, dtype=onp.float32).reshape(3, 4)
    a = np.array(x)
    mask = a > 5
    onp.testing.assert_allclose(a[mask].asnumpy(), x[x > 5])
    idx = np.array([2, 0], dtype="int32")
    onp.testing.assert_allclose(a[idx].asnumpy(), x[[2, 0]])
    onp.testing.assert_allclose(a[:, 1].asnumpy(), x[:, 1])
    nz = np.nonzero(a > 8)
    assert [i.asnumpy().tolist() for i in nz] == \
        [list(r) for r in onp.nonzero(x > 8)]


def test_where_take_sort_unique():
    x = onp.array([3, 1, 2, 3, 1], dtype=onp.float32)
    a = np.array(x)
    onp.testing.assert_allclose(np.where(a > 2, a, 0).asnumpy(),
                                onp.where(x > 2, x, 0))
    onp.testing.assert_allclose(np.sort(a).asnumpy(), onp.sort(x))
    onp.testing.assert_allclose(np.take(a, np.array([0, 4])).asnumpy(),
                                x[[0, 4]])
    u = np.unique(a)
    onp.testing.assert_allclose(u.asnumpy(), [1, 2, 3])


# ----------------------------------------------------------------- shape ops
def test_shape_manipulation():
    a = np.arange(24).reshape((2, 3, 4))
    assert np.transpose(a).shape == (4, 3, 2)
    assert np.moveaxis(a, 0, -1).shape == (3, 4, 2)
    assert np.concatenate([a, a], axis=1).shape == (2, 6, 4)
    assert np.stack([a, a]).shape == (2, 2, 3, 4)
    parts = np.split(np.arange(9), 3)
    assert len(parts) == 3 and parts[1].asnumpy().tolist() == [3, 4, 5]
    assert np.expand_dims(a, 0).shape == (1, 2, 3, 4)
    assert np.flip(np.arange(3)).asnumpy().tolist() == [2, 1, 0]
    assert np.pad(np.ones((2, 2)), 1).shape == (4, 4)
    g1, g2 = np.meshgrid(np.arange(2), np.arange(3))
    assert g1.shape == (3, 2) and g2.shape == (3, 2)
    assert np.atleast_2d(np.array(5.0)).shape == (1, 1)


def test_einsum_tensordot_linalg():
    x = onp.random.RandomState(3).rand(3, 3).astype(onp.float32)
    a = np.array(x)
    onp.testing.assert_allclose(np.einsum("ij,jk->ik", a, a).asnumpy(),
                                x @ x, rtol=1e-5)
    onp.testing.assert_allclose(np.trace(a).item(), onp.trace(x),
                                rtol=1e-5)
    spd = np.array(x @ x.T + 3 * onp.eye(3, dtype=onp.float32))
    onp.testing.assert_allclose(
        (np.linalg.cholesky(spd) @ np.linalg.cholesky(spd).T).asnumpy(),
        spd.asnumpy(), rtol=1e-4, atol=1e-5)
    inv = np.linalg.inv(spd)
    onp.testing.assert_allclose((spd @ inv).asnumpy(), onp.eye(3),
                                atol=1e-4)
    u, s, vt = np.linalg.svd(a)
    onp.testing.assert_allclose(
        (u * s[None, :]).asnumpy() @ vt.asnumpy(), x, atol=1e-4)
    w, v = np.linalg.eigh(spd)
    assert w.shape == (3,) and isinstance(v, np.ndarray)
    assert np.linalg.norm(a).shape == ()


# ------------------------------------------------------------------- random
def test_random_suite():
    np.random.seed(7)
    u = np.random.uniform(size=(100,))
    assert isinstance(u, np.ndarray) and 0 <= float(u.min()) \
        and float(u.max()) <= 1
    n = np.random.normal(2.0, 0.1, size=(500,))
    assert abs(float(n.mean()) - 2.0) < 0.05
    r = np.random.randint(0, 5, size=(50,))
    assert r.dtype == onp.int32 and int(r.max()) < 5
    c = np.random.choice(5, size=(10,))
    assert c.shape == (10,)
    p = np.random.permutation(6)
    assert sorted(p.asnumpy().tolist()) == [0, 1, 2, 3, 4, 5]
    x = np.arange(8)
    np.random.shuffle(x)
    assert sorted(x.asnumpy().tolist()) == list(range(8))
    # seeding is deterministic and shared with mx.random
    np.random.seed(3)
    a = np.random.uniform(size=(4,)).asnumpy()
    mx.random.seed(3)
    b = np.random.uniform(size=(4,)).asnumpy()
    onp.testing.assert_allclose(a, b)


# ----------------------------------------------------------------- autograd
def test_autograd_through_np_ops():
    a = np.array([1.0, 2.0, 3.0])
    a.attach_grad()
    with mx.autograd.record():
        y = np.sum(np.square(a) * 2)
    y.backward()
    assert isinstance(a.grad, nd.NDArray)
    onp.testing.assert_allclose(a.grad.asnumpy(), [4.0, 8.0, 12.0])


def test_gluon_forward_returns_np():
    """net(np_x) -> np output via _apply propagation; backward works."""
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = np.random.uniform(size=(2, 4))
    x.attach_grad()
    with mx.autograd.record():
        out = net(x)
        loss = np.sum(out * out)
    assert isinstance(out, np.ndarray)
    loss.backward()
    assert net.weight.grad() is not None
    assert x.grad.shape == (2, 4)


# --------------------------------------------------------------------- npx
def test_npx_mode_switches():
    assert not npx.is_np_array()
    npx.set_np()
    assert npx.is_np_array() and npx.is_np_shape()
    npx.reset_np()
    assert not npx.is_np_array()
    with npx.np_array(True):
        assert npx.is_np_array()
    assert not npx.is_np_array()

    @npx.use_np
    def f():
        return npx.is_np_array()
    assert f() and not npx.is_np_array()


def test_npx_nn_ops():
    x = np.array(onp.random.RandomState(5).randn(2, 6).astype(onp.float32))
    s = npx.softmax(x)
    onp.testing.assert_allclose(s.asnumpy().sum(1), onp.ones(2), rtol=1e-5)
    assert isinstance(s, np.ndarray)
    onp.testing.assert_allclose(
        npx.log_softmax(x).asnumpy(), onp.log(s.asnumpy()), rtol=1e-4,
        atol=1e-5)
    assert float(npx.relu(np.array([-1.0, 2.0])).asnumpy()[0]) == 0.0
    oh = npx.one_hot(np.array([0, 2], dtype="int32"), 3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]
    w = np.random.normal(size=(5, 6))
    fc = npx.fully_connected(x, w)
    assert fc.shape == (2, 5) and isinstance(fc, np.ndarray)
    bd = npx.batch_dot(np.ones((2, 3, 4)), np.ones((2, 4, 5)))
    assert bd.shape == (2, 3, 5)
    onp.testing.assert_allclose(
        npx.masked_softmax(x, np.array([[1, 1, 1, 0, 0, 0]] * 2))
        .asnumpy()[:, 3:], onp.zeros((2, 3)), atol=1e-6)
    assert npx.batch_flatten(np.ones((2, 3, 4))).shape == (2, 12)
    emb = npx.embedding(np.array([1, 0], dtype="int32"),
                        np.arange(6).reshape((3, 2)))
    assert emb.asnumpy().tolist() == [[2, 3], [0, 1]]


def test_npx_batch_norm_updates_running_stats():
    x = np.random.normal(5.0, 2.0, size=(16, 3))
    gamma, beta = np.ones(3), np.zeros(3)
    rm, rv = np.zeros(3), np.ones(3)
    y = npx.batch_norm(x, gamma, beta, rm, rv, training=True, axis=1,
                       momentum=0.0)
    assert y.shape == x.shape
    onp.testing.assert_allclose(rm.asnumpy(), x.asnumpy().mean(0),
                                rtol=1e-3)
    # inference path: stats untouched
    rm2 = np.array(rm.asnumpy())
    _ = npx.batch_norm(x, gamma, beta, rm2, rv, training=False, axis=1)
    onp.testing.assert_allclose(rm2.asnumpy(), rm.asnumpy())


def test_npx_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "arrs")
    npx.save(f, {"a": np.arange(4), "b": np.ones((2, 2))})
    out = npx.load(f)
    assert isinstance(out["a"], np.ndarray)
    onp.testing.assert_allclose(out["a"].asnumpy(), [0, 1, 2, 3])


def test_review_regressions():
    """Pinned fixes: floor_divide arity, single-output split/meshgrid,
    ==None semantics, Lomax pareto, util<->npx one global flag."""
    onp.testing.assert_allclose(
        np.floor_divide(np.array([7.0, -7.0]), 2).asnumpy(),
        onp.floor_divide(onp.array([7.0, -7.0]), 2))
    parts = np.split(np.arange(4).reshape(2, 2), 1)
    assert len(parts) == 1 and parts[0].shape == (2, 2)
    (g,) = np.meshgrid(np.arange(3))
    assert g.shape == (3,)
    (b,) = np.broadcast_arrays(np.ones((2, 2)))
    assert b.shape == (2, 2)
    a = np.arange(3)
    eq = a == None                                   # noqa: E711
    assert eq.dtype == onp.bool_ and not eq.asnumpy().any()
    assert (a != None).asnumpy().all()               # noqa: E711
    np.random.seed(0)
    p = np.random.pareto(3.0, size=(2000,))
    assert float(p.min()) >= 0.0 and float(p.min()) < 0.5  # Lomax support
    # one global np flag, visible across modules and threads
    import threading
    mx.util.set_np()
    seen = []
    t = threading.Thread(target=lambda: seen.append(npx.is_np_array()))
    t.start(); t.join()
    assert seen == [True] and mx.util.is_np_array()
    npx.reset_np()
    assert not mx.util.is_np_array()
    assert npx.gamma(np.array([4.0])).asnumpy()[0] == pytest.approx(6.0)


def test_np_array_function_interop():
    """np arrays slot into plain-numpy call sites via asnumpy()."""
    a = np.arange(3)
    assert onp.asarray(a.asnumpy()).sum() == 3
    assert np.allclose(a, a.copy())
    assert np.array_equal(a, np.array([0, 1, 2]))
    assert np.may_share_memory(a, a.copy())      # immutable buffer shared
    assert not np.may_share_memory(a, a + 0)


def test_np_arrays_under_jit_and_mesh():
    """np arrays hold ordinary jax.Arrays: they jit and shard like nd.
    Pins that the front end adds no Python-level obstacles to the
    compiled/SPMD paths."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    a = np.arange(16.0).reshape((8, 2))

    @jax.jit
    def f(x):
        return (x * 2).sum(axis=1)

    out = f(a._data)                       # raw buffer drops straight in
    onp.testing.assert_allclose(onp.asarray(out),
                                (a.asnumpy() * 2).sum(1))
    mesh = Mesh(onp.array(jax.devices()[:8]).reshape(8), ("dp",))
    sharded = jax.device_put(a._data, NamedSharding(mesh, P("dp", None)))
    b = np.ndarray(sharded)                # np view over a sharded array
    assert isinstance(b + 1, np.ndarray)
    onp.testing.assert_allclose((b + 1).asnumpy(), a.asnumpy() + 1)


def test_histogram_percentile_search_family():
    x = onp.random.RandomState(9).rand(200).astype(onp.float32)
    a = np.array(x)
    counts, edges = np.histogram(a, bins=8, range=(0, 1))
    ref_c, ref_e = onp.histogram(x, bins=8, range=(0, 1))
    onp.testing.assert_allclose(counts.asnumpy(), ref_c)
    onp.testing.assert_allclose(edges.asnumpy(), ref_e, rtol=1e-6)
    onp.testing.assert_allclose(np.percentile(a, 50).item(),
                                onp.percentile(x, 50), rtol=1e-5)
    onp.testing.assert_allclose(np.quantile(a, 0.25).item(),
                                onp.quantile(x, 0.25), rtol=1e-5)
    bins = np.array([0.25, 0.5, 0.75])
    onp.testing.assert_allclose(np.digitize(a, bins).asnumpy(),
                                onp.digitize(x, bins.asnumpy()))
    srt = np.sort(a)
    onp.testing.assert_allclose(
        np.searchsorted(srt, np.array([0.1, 0.9])).asnumpy(),
        onp.searchsorted(onp.sort(x), [0.1, 0.9]))
    assert np.count_nonzero(np.array([0, 1, 2, 0])).item() == 2
    onp.testing.assert_allclose(
        np.argwhere(np.array([0, 3, 0, 5])).asnumpy(), [[1], [3]])
    assert np.flatnonzero(np.array([0, 1, 0, 2])).asnumpy().tolist() == [1, 3]
    bc = np.bincount(np.array([0, 1, 1, 4], dtype="int32"))
    assert bc.asnumpy().tolist() == [1, 2, 0, 0, 1]
    onp.testing.assert_allclose(
        np.interp(np.array([0.5]), np.array([0.0, 1.0]),
                  np.array([10.0, 20.0])).asnumpy(), [15.0])
