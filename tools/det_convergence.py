"""Detection convergence evidence (VERDICT r4 item 8): train each
detector for a few hundred steps on a LEARNABLE synthetic dataset
(rendered colored rectangles — class == color), record the loss curve,
and sanity-check decoded predictions on held-out scenes.

Usage:  python tools/det_convergence.py [--model ssd|rcnn]
            [--steps N] [--batch N] [--input N] [--report PATH]

The loss curve + eval stats print as one JSON line for docs/PERF.md.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# 4 high-contrast fill colors == 4 classes
_COLORS = np.array([[0.9, 0.1, 0.1], [0.1, 0.9, 0.1],
                    [0.15, 0.15, 0.95], [0.9, 0.9, 0.1]], np.float32)
NUM_CLASSES = len(_COLORS)

# FRCNN scene geometry, shared by train AND held-out eval: boxes sized
# to overlap the model's stride-16 RPN anchors at small inputs
RCNN_SCENE_KW = {"m_boxes": 2, "box_range": (0.4, 0.75)}


def make_scenes(n, size, m_boxes=3, seed=0, box_range=(0.25, 0.5)):
    """Render n scenes of m colored rectangles on noise background.
    Returns images (n, size, size, 3) f32 and labels (n, m, 5)
    [cls, x1, y1, x2, y2] normalized, -1-padded. box_range scales the
    rectangles — the FRCNN run uses larger boxes so the planted objects
    overlap the model's stride-16 RPN anchor sizes at small inputs."""
    rs = np.random.RandomState(seed)
    imgs = rs.uniform(0.3, 0.5, (n, size, size, 3)).astype(np.float32)
    labels = np.full((n, m_boxes, 5), -1.0, np.float32)
    for i in range(n):
        placed = []
        for j in range(m_boxes):
            # rejection-sample placements so later rectangles cannot
            # paint over earlier ones (an occluded gt box would count
            # as a miss in the recall denominator regardless of model
            # quality); scenes that can't fit another box keep the -1
            # pad row, which every consumer already skips
            for _ in range(20):
                w, h = rs.uniform(box_range[0], box_range[1], 2)
                x1 = rs.uniform(0.05, 0.95 - w)
                y1 = rs.uniform(0.05, 0.95 - h)
                cand = (x1, y1, x1 + w, y1 + h)
                if all(_iou(cand, p) < 0.1 for p in placed):
                    break
            else:
                continue
            placed.append(cand)
            c = rs.randint(NUM_CLASSES)
            px1, py1 = int(x1 * size), int(y1 * size)
            px2, py2 = int((x1 + w) * size), int((y1 + h) * size)
            imgs[i, py1:py2, px1:px2] = _COLORS[c] \
                + rs.uniform(-0.05, 0.05, 3).astype(np.float32)
            labels[i, j] = [c, x1, y1, x1 + w, y1 + h]
    return imgs, labels


def _iou(a, b):
    ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
    ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
    iw, ih = max(0.0, ix2 - ix1), max(0.0, iy2 - iy1)
    inter = iw * ih
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) \
        - inter
    return inter / max(ua, 1e-9)


def run_ssd(args):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import extract_pure_fn
    from mxnet_tpu.models.ssd import SSD, ssd_decode
    from mxnet_tpu.ops import detection_ops as D
    from bench_util import make_sgd_step

    size, batch = args.input, args.batch
    net = SSD(num_classes=NUM_CLASSES,
              backbone_layers=18 if size < 256 else 50, input_size=size)
    net.initialize(mx.init.Xavier())
    warm = mx.nd.array(np.zeros((batch, size, size, 3), np.float32))
    net(warm)
    fwd, params = extract_pure_fn(net, warm, training=True)
    aux_idx = list(fwd.aux_indices)
    anchors = jnp.asarray(net.anchors)

    n_train = args.batch * 24
    imgs, labels = make_scenes(n_train, size, seed=0)
    t_cls, t_loc, t_msk = [], [], []
    for s in range(0, n_train, batch):
        ct, lt, lm = D.multibox_target(
            anchors, jnp.asarray(labels[s:s + batch]), 0.5)
        t_cls.append(ct); t_loc.append(lt); t_msk.append(lm)

    def loss_fn(p, xb, ct, lt, lm):
        (cls_p, loc_p), aux = fwd(p, xb)
        cls_p = cls_p.astype(jnp.float32)
        loc_p = loc_p.astype(jnp.float32).reshape(ct.shape[0], -1, 4)
        lp = jax.nn.log_softmax(cls_p, axis=-1)
        l_cls = -jnp.mean(jnp.take_along_axis(
            lp, ct.astype(jnp.int32)[..., None], -1))
        d = (loc_p - lt) * lm
        l_loc = jnp.mean(jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                                   jnp.abs(d) - 0.5))
        return l_cls + l_loc, aux

    step = make_sgd_step(loss_fn, aux_idx, lr=args.lr, mu=0.9)
    mom = [jnp.zeros_like(p) for p in params]
    curve = []
    n_b = len(t_cls)
    t0 = time.time()
    for it in range(args.steps):
        b = it % n_b
        xb = jnp.asarray(imgs[b * batch:(b + 1) * batch])
        params, mom, loss = step(params, mom, xb, t_cls[b], t_loc[b],
                                 t_msk[b])
        if it % 20 == 0 or it == args.steps - 1:
            curve.append([it, round(float(loss), 4)])
            print(f"[ssd] step {it} loss {float(loss):.4f} "
                  f"({time.time()-t0:.0f}s)", file=sys.stderr)

    # held-out eval through the real decode (softmax -> MultiBoxDetection
    # NMS) — predictions must be finite, in-bounds, and hit the planted
    # boxes with the right class. Reuses the training fwd with the
    # TRAINED param list (same extract, same ordering); batch-stat BN is
    # fine for this sanity check.
    ev_imgs, ev_labels = make_scenes(batch, size, seed=99)
    (cls_p, loc_p), _ = fwd(params, jnp.asarray(ev_imgs))
    det = ssd_decode(mx.nd.NDArray(cls_p.astype(jnp.float32)),
                     mx.nd.NDArray(loc_p.astype(jnp.float32)),
                     net.anchors).asnumpy()
    hits = total = 0
    finite = bool(np.isfinite(det).all())
    for i in range(batch):
        keep = det[i][det[i][:, 0] >= 0]
        keep = keep[keep[:, 1] > 0.3][:8]
        for (c, x1, y1, x2, y2) in ev_labels[i]:
            if c < 0:
                continue
            total += 1
            for row in keep:
                if int(row[0]) == int(c) and \
                        _iou(row[2:6], (x1, y1, x2, y2)) > 0.3:
                    hits += 1
                    break
    return {"model": "ssd", "input": size, "batch": batch,
            "steps": args.steps, "loss_curve": curve,
            "final_loss": curve[-1][1], "detections_finite": finite,
            "holdout_recall@iou0.3": round(hits / max(total, 1), 3)}


def run_rcnn(args):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    import bench_det
    from mxnet_tpu.ops import detection_ops as D
    from bench_util import make_sgd_step
    from mxnet_tpu.gluon.block import extract_pure_fn

    size, batch = args.input, args.batch
    # reuse the benched two-stage step builder wholesale, then retrain it
    # on varying rendered scenes (build_step bakes one batch; the jitted
    # step accepts any same-shape data)
    step, params, mom, data0, (net, fwd) = bench_det.build_rcnn_step(
        batch, size, return_parts=True)
    from mxnet_tpu.models.faster_rcnn import FasterRCNN  # for anchors

    n_train = batch * 24
    imgs, labels = make_scenes(n_train, size, seed=0, **RCNN_SCENE_KW)
    # bench_det's step takes (x, gt_pixels, rpn_cls_t, rpn_box_t,
    # rpn_box_m); regenerate those per chunk
    net_like = FasterRCNN(num_classes=20,
                          backbone_layers=18 if size < 256 else 50,
                          input_size=size)
    anchors_n = jnp.asarray(net_like.anchors, jnp.float32) / size
    batches = []
    for s in range(0, n_train, batch):
        lab = labels[s:s + batch].copy()
        gt_px = lab.copy()
        gt_px[..., 1:] *= size
        gt_px[gt_px[..., 0] < 0] = -1
        gt_n = jnp.asarray(lab, jnp.float32)
        rct, rbt, rbm = D.multibox_target(anchors_n, gt_n, 0.5,
                                          variances=(1, 1, 1, 1))
        batches.append((jnp.asarray(imgs[s:s + batch], jnp.bfloat16),
                        jnp.asarray(gt_px, jnp.float32), rct, rbt, rbm))

    curve = []
    t0 = time.time()
    for it in range(args.steps):
        b = batches[it % len(batches)]
        params, mom, loss = step(params, mom, *b)
        if it % 20 == 0 or it == args.steps - 1:
            curve.append([it, round(float(loss), 4)])
            print(f"[rcnn] step {it} loss {float(loss):.4f} "
                  f"({time.time()-t0:.0f}s)", file=sys.stderr)
    # held-out sanity: after training, the RPN's decoded+NMS'd proposals
    # must cover the planted boxes (recall@IoU0.5) and be finite
    ev_imgs, ev_labels = make_scenes(batch, size, seed=99,
                                     **RCNN_SCENE_KW)
    ev_gt_px = ev_labels.copy()
    ev_gt_px[..., 1:] *= size
    ev_gt_px[ev_labels[..., 0] < 0] = -1
    (obj, deltas, *_rest), _ = fwd(
        params, jnp.asarray(ev_imgs, jnp.bfloat16),
        jnp.asarray(ev_gt_px, jnp.float32))
    props, _scores = net.rpn_proposals(
        mx.nd.NDArray(obj), mx.nd.NDArray(deltas), pre_nms=512)
    props = props.asnumpy()
    finite = bool(np.isfinite(props).all())
    hits = total = 0
    for i in range(batch):
        for (c, x1, y1, x2, y2) in ev_gt_px[i]:
            if c < 0:
                continue
            total += 1
            if any(_iou(p, (x1, y1, x2, y2)) > 0.5 for p in props[i]):
                hits += 1
    return {"model": "rcnn", "input": size, "batch": batch,
            "steps": args.steps, "loss_curve": curve,
            "final_loss": curve[-1][1],
            "proposals_finite": finite,
            "proposal_recall@iou0.5": round(hits / max(total, 1), 3)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=("ssd", "rcnn"), default="ssd")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--input", type=int, default=None)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    on_tpu = jax.default_backend() == "tpu"
    if args.input is None:
        args.input = 256 if on_tpu else 128
    if args.batch is None:
        args.batch = 16 if on_tpu else 4
    res = (run_ssd if args.model == "ssd" else run_rcnn)(args)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
