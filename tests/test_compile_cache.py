"""Persistent compilation cache round-trip (ISSUE 11 acceptance): with
`MXTPU_COMPILE_CACHE` set, a second COLD process compiling the same
captured step hits the disk cache (`compile_cache_hits` >= 1) and sees
measurably lower first-step latency; with the cache disabled behaviour
is bitwise-identical (same losses, zero cache lookups)."""
import json
import os
import subprocess
import sys
import textwrap

# the worker compiles a captured MLP step big enough that a cold XLA
# compile clearly dominates a warm disk-cache deserialisation
_WORKER = textwrap.dedent("""
    import json, os, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.observability import compilex

    rng = np.random.RandomState(0)
    X = nd.array(rng.randn(32, 64).astype(np.float32))
    y = nd.array(rng.randint(0, 16, 32).astype(np.float32))
    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(16))
    net.initialize(mx.init.Xavier())
    net(X)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9})
    step = tr.capture(lambda a, b: lossf(net(a), b).mean())
    t0 = time.monotonic()
    L1 = step(X, y)
    first_s = time.monotonic() - t0
    L2 = step(X, y)
    hits, misses = compilex.compile_cache_stats()
    print(json.dumps({
        "first_step_s": first_s,
        "hits": hits, "misses": misses,
        "cache_dir": compilex.compilation_cache_dir(),
        "loss1": float(L1.asnumpy()), "loss2": float(L2.asnumpy()),
        "fallback": step.last_fallback_reason,
    }))
""")


def _run_worker(tmp_path, cache_dir):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    repo = os.path.join(os.path.dirname(__file__), "..")
    env["PYTHONPATH"] = os.path.abspath(repo) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # isolate the measurement: no HLO-inspection recompiles, and no
    # stray cache dir inherited from the invoking environment
    env["MXTPU_HLO_TELEMETRY"] = "0"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    if cache_dir is None:
        env.pop("MXTPU_COMPILE_CACHE", None)
    else:
        env["MXTPU_COMPILE_CACHE"] = str(cache_dir)
    proc = subprocess.run([sys.executable, str(script)],
                          stdout=subprocess.PIPE,
                          stderr=subprocess.DEVNULL,
                          env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout.decode(errors="replace")
    line = [l for l in proc.stdout.decode().splitlines()
            if l.strip().startswith("{")][-1]
    return json.loads(line)


def test_compile_cache_cold_warm_round_trip(tmp_path):
    cache = tmp_path / "cc"
    cold = _run_worker(tmp_path, cache)
    assert cold["fallback"] is None
    assert str(cold["cache_dir"]) == str(cache)
    assert cold["hits"] == 0            # nothing on disk yet
    assert cold["misses"] >= 1          # ...but the cache was consulted
    assert len(os.listdir(cache)) > 0   # entries persisted

    warm = _run_worker(tmp_path, cache)
    # the second cold PROCESS deserialises from disk instead of
    # re-running XLA...
    assert warm["hits"] >= 1
    # ...and its first captured step is measurably faster
    assert warm["first_step_s"] < cold["first_step_s"]

    # cache disabled: no lookups, and training is bitwise-identical
    off = _run_worker(tmp_path, None)
    assert off["cache_dir"] in (None, "None")
    assert off["hits"] == 0 and off["misses"] == 0
    for k in ("loss1", "loss2"):
        assert off[k] == cold[k] == warm[k]


def test_set_compilation_cache_api_round_trip(tmp_path):
    """mx.set_compilation_cache in-process: enable -> dir created and
    readable back; None -> disabled. (Restores the prior setting.)"""
    import mxnet_tpu as mx
    from mxnet_tpu.observability import compilex

    prev = compilex.compilation_cache_dir()
    try:
        d = mx.set_compilation_cache(tmp_path / "cc_api")
        assert os.path.isdir(d)
        assert str(compilex.compilation_cache_dir()) == str(d)
        assert mx.set_compilation_cache(None) is None
        assert compilex.compilation_cache_dir() in (None, "")
    finally:
        if prev:
            mx.set_compilation_cache(prev)
        else:
            mx.set_compilation_cache(None)
