"""Base utilities: errors, dtype handling, and the native-runtime bridge.

Reference parity: python/mxnet/base.py (MXNetError, c_api handles). Here the
"C API" is the optional native dependency engine in cpp/ (loaded via ctypes by
mxnet_tpu.engine); tensors live in PJRT-managed HBM so no handle table exists.
"""
from __future__ import annotations

import numpy as np

__all__ = ["MXNetError", "numeric_types", "integer_types", "string_types",
           "mx_real_t", "_as_list", "_np_dtype",
           "py_str", "c_str"]


class MXNetError(RuntimeError):
    """Error raised by mxnet_tpu — parity with mxnet.base.MXNetError."""


numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)
string_types = (str,)

mx_real_t = np.float32


def _as_list(obj):
    """Return obj wrapped in a list if it is not already a list/tuple."""
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


_DTYPE_ALIASES = {
    "float": np.float32,
    "double": np.float64,
    None: np.float32,
}


def _np_dtype(dtype):
    """Normalise a user-supplied dtype to a numpy dtype object."""
    if dtype in _DTYPE_ALIASES:
        return np.dtype(_DTYPE_ALIASES[dtype])
    import jax.numpy as jnp  # local import: keep base import-light
    if dtype is jnp.bfloat16 or dtype == "bfloat16":
        return jnp.bfloat16
    return np.dtype(dtype)


def py_str(x):
    """bytes -> str (reference: base.py py_str ctypes helper)."""
    return x.decode("utf-8") if isinstance(x, bytes) else str(x)


def c_str(x):
    """str -> ctypes char_p (reference: base.py c_str)."""
    import ctypes
    return ctypes.c_char_p(x.encode("utf-8"))
