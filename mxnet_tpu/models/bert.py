"""BERT for MLM+NSP pretraining (GluonNLP parity: bert_12_768_12 /
bert_24_1024_16; reference behavior from gluonnlp's model.bert — rebuilt
TPU-first, not translated).

TPU-first choices:
  * fused QKV projection — one (D, 3D) matmul feeding the MXU instead of
    three small ones;
  * attention rides ops.pallas_kernels.flash_attention (Pallas on TPU,
    XLA reference off-TPU); padding masks ride the kernel's kv_lengths
    scalar-prefetch path — no dense (B,1,1,S) mask is ever built;
  * static-shape MLM: `masked_positions` (B, P) with a fixed prediction
    budget P, gathered with take_along_axis — no dynamic shapes under jit;
  * everything is a HybridBlock: `hybridize()` compiles the whole encoder
    into one XLA executable.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply
from ..gluon import nn
from ..gluon.block import HybridBlock, is_symbolic as _is_symbol
from ..ops.pallas_kernels import flash_attention
from ._sym_attention import sym_attention

__all__ = ["BERTModel", "BERTEncoder", "BERTEncoderLayer",
           "MultiHeadSelfAttention", "PositionwiseFFN", "BERTForPretraining",
           "bert_base", "bert_large", "get_bert"]


def _split_heads(x, n_heads):
    b, s, d = x.shape
    return x.reshape(b, s, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * dh)


class MultiHeadSelfAttention(HybridBlock):
    """Self-attention with fused QKV and flash attention."""

    def __init__(self, units, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        if units % num_heads != 0:
            raise MXNetError("units must be divisible by num_heads")
        self._units = units
        self._num_heads = num_heads
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, in_units=units,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, in_units=units,
                                 prefix="proj_")
            self.dropout = nn.Dropout(dropout)

    def _symbolic_forward(self, F, x, valid_length):
        """Symbolic attention for export: the flash kernel decomposed into
        named graph ops so ONNX export and SymbolBlock reload see a
        serialisable graph (shared decomposition:
        models/_sym_attention.py; numerics match the eager path)."""
        d = self._units
        qkv = self.qkv(x)
        q = F.slice_axis(qkv, axis=-1, begin=0, end=d)
        k = F.slice_axis(qkv, axis=-1, begin=d, end=2 * d)
        v = F.slice_axis(qkv, axis=-1, begin=2 * d, end=3 * d)
        out = sym_attention(F, q, k, v, self._num_heads, d,
                            length=valid_length)
        return self.dropout(self.proj(out))

    def hybrid_forward(self, F, x, valid_length=None):
        if _is_symbol(x):
            return self._symbolic_forward(F, x, valid_length)
        qkv = self.qkv(x)
        h = self._num_heads

        def attn(qkv_raw, *maybe_vl):
            q, k, v = jnp.split(qkv_raw, 3, axis=-1)
            q, k, v = (_split_heads(t, h) for t in (q, k, v))
            if maybe_vl:
                # padding mask as per-row kv length: rides the Pallas flash
                # kernel's scalar-prefetch masked path (XLA mask fallback
                # off-TPU) instead of a dense (B,1,1,S) additive mask
                out = flash_attention(
                    q, k, v, kv_lengths=maybe_vl[0].astype(jnp.int32))
            else:
                out = flash_attention(q, k, v)
            return _merge_heads(out)

        inputs = [qkv] + ([valid_length] if valid_length is not None else [])
        out = _apply(attn, inputs)
        return self.dropout(self.proj(out))


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units,
                                 activation=activation, prefix="ffn1_")
            self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size,
                                 prefix="ffn2_")
            self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x):
        return self.dropout(self.ffn2(self.ffn1(x)))


class BERTEncoderLayer(HybridBlock):
    """Post-LN transformer layer (BERT convention)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.attention = MultiHeadSelfAttention(units, num_heads, dropout)
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ffn = PositionwiseFFN(units, hidden_size, dropout)
            self.ln2 = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, valid_length=None):
        x = self.ln1(x + self.attention(x, valid_length))
        return self.ln2(x + self.ffn(x))


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads,
                 max_length=512, dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units), init="zeros")
            self.dropout = nn.Dropout(dropout)
            self.ln = nn.LayerNorm(in_channels=units)
            self.layers = nn.HybridSequential(prefix="layers_")
            with self.layers.name_scope():
                for _ in range(num_layers):
                    self.layers.add(BERTEncoderLayer(
                        units, hidden_size, num_heads, dropout))

    def hybrid_forward(self, F, x, valid_length=None, position_weight=None):
        if _is_symbol(x):
            # static seq length via shape inference (shaped input Variables)
            try:
                _, out_shapes, _ = x.infer_shape()
                seq_len = out_shapes[0][1]
            except Exception as e:
                raise MXNetError(
                    "BERT symbolic trace needs shaped input Variables "
                    "(sym.Variable('token_ids', shape=(B, S))) so the "
                    f"position slice is static: {e!r}") from e
            pos = F.expand_dims(F.slice_axis(
                position_weight, axis=0, begin=0, end=int(seq_len)), 0)
            x = F.broadcast_add(x, pos)
        else:
            seq_len = x.shape[1]

            def add_pos(a, p):
                return a + p[:seq_len][None]

            x = _apply(add_pos, [x, position_weight])
        x = self.dropout(self.ln(x))
        for layer in self.layers:
            x = layer(x, valid_length)
        return x


class BERTModel(HybridBlock):
    """Token/segment embeddings + encoder + pooler + tied MLM decoder.

    forward(token_ids, segment_ids, valid_length=None, masked_positions=None)
      -> (sequence_output, pooled_output[, mlm_scores])
    matching gluonnlp's BERTModel output contract.
    """

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, dropout=0.1,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self.vocab_size = vocab_size
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(2, units,
                                                 prefix="token_type_embed_")
            self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                       num_heads, max_length, dropout)
            self.pooler = nn.Dense(units, flatten=False, activation="tanh",
                                   in_units=units, prefix="pooler_")
            # MLM head: transform + LN; decoder shares word_embed weight
            self.mlm_dense = nn.Dense(units, flatten=False, activation="gelu",
                                      in_units=units, prefix="mlm_dense_")
            self.mlm_ln = nn.LayerNorm(in_channels=units, prefix="mlm_ln_")
            self.mlm_bias = self.params.get("mlm_bias", shape=(vocab_size,),
                                            init="zeros")

    def hybrid_forward(self, F, token_ids, segment_ids, valid_length=None,
                       masked_positions=None, mlm_bias=None):
        # mlm_bias arrives as a registered-param kwarg; decode_mlm reads it
        # through Parameter.data() so the tied path stays uniform
        if masked_positions is not None and _is_symbol(token_ids):
            raise MXNetError(
                "symbolic BERT trace covers the encoder surface "
                "(sequence_output, pooled_output); MLM decode is eager-only")
        x = self.word_embed(token_ids) + self.token_type_embed(segment_ids)
        seq = self.encoder(x, valid_length)
        pooled = self.pooler(seq.slice_axis(axis=1, begin=0, end=1)
                             .reshape((0, -1)))
        if masked_positions is None:
            return seq, pooled
        mlm = self.decode_mlm(seq, masked_positions)
        return seq, pooled, mlm

    def decode_mlm(self, seq, masked_positions):
        """Gather (B, P) positions, transform, project to vocab with the
        tied embedding matrix."""
        def gather(s, pos):
            return jnp.take_along_axis(
                s, pos[:, :, None].astype(jnp.int32), axis=1)

        at = _apply(gather, [seq, masked_positions])
        h = self.mlm_ln(self.mlm_dense(at))
        # Parameter.data() resolves to the traced value under hybridization,
        # so weight tying works in both eager and compiled paths
        w = self.word_embed.weight.data()
        b = self.mlm_bias.data()

        def project(hh, ww, bb):
            return jnp.einsum("bpd,vd->bpv", hh, ww) + bb

        return _apply(project, [h, w, b])


class BERTForPretraining(HybridBlock):
    """MLM + NSP heads on BERTModel (gluonnlp BERTForPretrain contract)."""

    def __init__(self, bert: BERTModel, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert
            self.nsp = nn.Dense(2, flatten=False, in_units=bert._units,
                                prefix="nsp_")

    def hybrid_forward(self, F, token_ids, segment_ids, valid_length,
                       masked_positions):
        seq, pooled, mlm = self.bert(token_ids, segment_ids, valid_length,
                                     masked_positions)
        return mlm, self.nsp(pooled)


class BERTClassifier(HybridBlock):
    """Sentence(-pair) classification head on the pooled output
    (gluonnlp BERTClassifier contract: dropout -> dense(num_classes))."""

    def __init__(self, bert: BERTModel, num_classes=2, dropout=0.1,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert
            self.dropout = nn.Dropout(dropout) if dropout else None
            self.classifier = nn.Dense(num_classes, flatten=False,
                                       in_units=bert._units,
                                       prefix="classifier_")

    def hybrid_forward(self, F, token_ids, segment_ids, valid_length=None):
        _, pooled = self.bert(token_ids, segment_ids, valid_length)
        if self.dropout is not None:
            pooled = self.dropout(pooled)
        return self.classifier(pooled)


class BERTRegression(HybridBlock):
    """Single-value regression head on the pooled output (gluonnlp
    BERTRegression contract)."""

    def __init__(self, bert: BERTModel, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.bert = bert
            self.dropout = nn.Dropout(dropout) if dropout else None
            self.regression = nn.Dense(1, flatten=False,
                                       in_units=bert._units,
                                       prefix="regression_")

    def hybrid_forward(self, F, token_ids, segment_ids, valid_length=None):
        _, pooled = self.bert(token_ids, segment_ids, valid_length)
        if self.dropout is not None:
            pooled = self.dropout(pooled)
        return self.regression(pooled)


_SPECS = {
    # name: (num_layers, units, hidden, heads)
    "bert_12_768_12": (12, 768, 3072, 12),
    "bert_24_1024_16": (24, 1024, 4096, 16),
}


def get_bert(model_name="bert_12_768_12", vocab_size=30522, max_length=512,
             dropout=0.1, **kwargs):
    if model_name not in _SPECS:
        raise MXNetError(f"unknown bert spec {model_name}")
    layers, units, hidden, heads = _SPECS[model_name]
    return BERTModel(vocab_size=vocab_size, units=units, hidden_size=hidden,
                     num_layers=layers, num_heads=heads, max_length=max_length,
                     dropout=dropout, **kwargs)


def bert_base(**kwargs):
    return get_bert("bert_12_768_12", **kwargs)


def bert_large(**kwargs):
    return get_bert("bert_24_1024_16", **kwargs)
