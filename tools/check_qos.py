#!/usr/bin/env python
"""Multi-tenant engine QoS gate (ISSUE 7 acceptance; same tier-1 wiring
pattern as check_dispatch/chaos_check).

Three phases:

  1. **Dispatch fairness** (deterministic, 1-worker instances of BOTH
     engine implementations): a high-priority push dispatches before the
     entire queued backlog no matter how stale it is (promotion FLOORS
     at the high class, native high wins ties), while a background task
     aged past the class distance beats fresh NORMAL work — priority
     preemption with starvation bounded one class down.

  2. **FIFO control** (set_qos(False), real engine): under the same
     background flood the gate's starvation bound MUST be exceeded —
     proving the zero in phase 3 is a measurement, not a dead bound.

  3. **Chaos soak**: continuous decode (engine-driven `serve.Server`) +
     a sustained background engine flood + injected `engine.task` and
     `serve.decode` faults + a mid-flight TaskGroup cancellation + a
     DevicePrefetcher closed mid-epoch, asserting

       * decode output BITWISE-stable vs an unloaded inline run,
       * ZERO high-priority dispatch waits past the aging bound
         (starved decode turns) and bounded dispatch-wait p99,
       * zero leaked KV pages, zero live task groups, prefetch staging
         depth back to baseline,
       * cancelled tasks recorded as failures NOWHERE, race detector
         quiet.

Standalone:

    JAX_PLATFORMS=cpu python tools/check_qos.py

exit 0 = QoS invariants hold, 1 = violation (details on stderr).
Prints one JSON line with the measured numbers on stdout.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

_REPO_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
if _REPO_ROOT not in sys.path:   # mxnet_tpu + bench_util, however invoked
    sys.path.insert(0, _REPO_ROOT)

AGING_MS = 100
# a decode turn is "starved" when its dispatch wait exceeds the full
# aging ladder (a ready task is promoted one class per interval) plus
# scheduler slack — generous for CI noise, far below what the FIFO
# control measures under the same flood
STARVE_BOUND_S = 3 * (AGING_MS / 1000.0) + 0.2
BG_TASK_S = 0.02           # background task duration (sleep — IO-like)
BG_BACKLOG_PER_WORKER = 48  # sustained queued background tasks per worker


def _phase_fairness(errors):
    """Deterministic 1-worker ordering on BOTH engine implementations."""
    from mxnet_tpu.engine import _PyEngine

    engines = [("py", _PyEngine(1, aging_ms=AGING_MS))]
    try:
        from mxnet_tpu._native import NativeEngine
        neng = NativeEngine(1)
        neng.set_aging_ms(AGING_MS)
        engines.append(("native", neng))
    except Exception:
        # native build optional (no C++ toolchain): the Python-engine
        # invariants still gate — mirrors engine._get()'s silent fallback
        # and the shim's `>= {"py"}` tolerance; environments that REQUIRE
        # the native engine pin it via test_native_engine_loads instead
        pass

    for name, eng in engines:
        order = []
        gate = threading.Event()
        eng.push(gate.wait)
        time.sleep(0.02)
        eng.push(lambda: order.append("bg-aged"), priority=2)
        time.sleep(3.5 * AGING_MS / 1000.0)    # ages past the class distance
        for i in range(3):
            eng.push(lambda i=i: order.append(f"norm{i}"), priority=1)
        eng.push(lambda: order.append("hi"), priority=0)
        gate.set()
        eng.wait_for_all()
        # high first (native class wins ties with promoted work), the
        # aged background next (promotion over fresh normal), then the
        # normal backlog in FIFO order
        want = ["hi", "bg-aged", "norm0", "norm1", "norm2"]
        if order != want:
            errors.append(f"{name} engine fairness violated: expected "
                          f"{want}, got {order}")
        eng.close()    # transient instance: stop its worker threads
    return {"fairness_engines": [n for n, _ in engines]}


def _background_flood(target):
    """The soak/control backlog: `bench_util.BackgroundEngineLoad` (one
    shared generator with `bench_serve.py --background-train`, so the
    gate and the bench measure the same contention)."""
    from bench_util import BackgroundEngineLoad
    return BackgroundEngineLoad(target, task_s=BG_TASK_S)


def _probe_wait(engine):
    """Push one high-priority probe; returns its dispatch wait in
    seconds (None when the probe was killed by an injected fault)."""
    t_push = time.monotonic()
    fut = engine.push(lambda: time.monotonic() - t_push,
                      priority=engine.PRIORITY_HIGH)
    try:
        res = fut.result(timeout=60)
    except Exception:
        return None                     # injected engine.task fault
    return None if engine.skipped(res) else res


def _phase_fifo_control(errors):
    """Without QoS (every push NORMAL), the same flood must blow the
    starvation bound — otherwise the soak's zero is vacuous."""
    from mxnet_tpu import engine

    workers = engine.num_workers()
    prev_qos = engine.set_qos(False)
    try:
        with _background_flood(workers * BG_BACKLOG_PER_WORKER):
            time.sleep(0.3)             # let the backlog build
            waits = [w for w in (_probe_wait(engine) for _ in range(3))
                     if w is not None]
    finally:
        engine.set_qos(prev_qos)
        engine.wait_for_all()
    worst = max(waits) if waits else 0.0
    if worst <= STARVE_BOUND_S:
        errors.append(f"FIFO control did not exceed the starvation bound "
                      f"({worst:.3f}s <= {STARVE_BOUND_S}s): the soak's "
                      f"zero-starvation assertion would be vacuous")
    return {"fifo_control_worst_wait_s": round(worst, 4)}


def _build_server(engine_driven, max_retries=1):
    import mxnet_tpu as mx
    from mxnet_tpu.models.transformer import TransformerNMT

    mx.random.seed(5)
    model = TransformerNMT(32, units=16, hidden=32, num_layers=1,
                           num_heads=2, max_length=32, dropout=0.0)
    model.initialize()
    return mx.serve.Server(model, slots=3, page_size=4, max_src_len=8,
                           max_new_tokens=8, max_queue=64,
                           max_retries=max_retries,
                           engine_driven=engine_driven)


def _workload(n=8):
    import numpy as np
    rng = np.random.RandomState(3)
    return [(rng.randint(4, 32, (int(rng.randint(3, 8)),)).astype(np.int32),
             int(rng.choice([3, 5, 8]))) for _ in range(n)]


def _phase_soak(errors):
    import mxnet_tpu  # noqa: F401 — full framework up before fault arming
    from mxnet_tpu import engine
    from mxnet_tpu.fault import injection as finj
    from mxnet_tpu.observability import registry
    from mxnet_tpu.prefetch import DevicePrefetcher

    reqs = _workload()

    # -- clean reference: inline, unloaded, fault-free -------------------
    srv = _build_server(engine_driven=False)
    clean = []
    for src, max_new in reqs:
        clean.append(srv.submit(src, max_new_tokens=max_new))
    srv.scheduler.run_until_idle()
    clean_tokens = [h.result() for h in clean]
    srv.close()

    # -- chaos soak ------------------------------------------------------
    engine.wait_for_all()
    prev_aging = engine.set_aging_ms(AGING_MS)
    engine.set_debug(True)
    engine.clear_error()
    depth_gauge = registry().gauge("prefetch_depth")
    depth_before = depth_gauge.value or 0
    workers = engine.num_workers()
    srv = _build_server(engine_driven=True, max_retries=8)
    waits = []
    handles = []
    try:
        with _background_flood(workers * BG_BACKLOG_PER_WORKER):
            time.sleep(0.2)
            # seeded faults: random engine-task kills (hit background
            # tasks, probes AND serve loop tasks — the loop must re-arm)
            # plus two decode-batch kills the scheduler retries
            finj.inject("engine.task", prob=0.03, seed=7)
            finj.inject("serve.decode", at=[4, 9])
            handles = [srv.submit(src, max_new_tokens=max_new)
                       for src, max_new in reqs]

            # mid-flight group cancellation: a queued victim group dies
            # as a unit while decode + flood + faults are all live (the
            # victims sit at the tail of the deep background backlog, so
            # the immediate cancel always beats their dispatch; no dep
            # task is used — a dep could eat an injected fault and poison
            # the victims into exceptions instead of clean CANCELLED)
            def victim_task():
                time.sleep(0.005)

            victim = engine.TaskGroup("qos.victim")
            vfuts = [victim.push(victim_task,
                                 priority=engine.PRIORITY_BACKGROUND)
                     for _ in range(12)]
            victim.cancel()

            # a device-input pipeline abandoned mid-epoch during the soak
            pf = DevicePrefetcher(iter([{"x": [float(i)]}
                                        for i in range(32)]), depth=2)
            try:
                next(pf)
                next(pf)
            except BaseException:
                pass                    # an injected staging fault is fine
            pf.close()

            # high-priority probes measure decode-class dispatch latency
            # while everything above is in flight; at least 25 probes run
            # under the sustained flood even when the tiny request trace
            # drains early
            deadline = time.monotonic() + 120
            while not all(h.done() for h in handles) or len(waits) < 25:
                if time.monotonic() > deadline:
                    errors.append("soak did not drain within 120s")
                    break
                w = _probe_wait(engine)
                if w is not None:
                    waits.append(w)
                time.sleep(0.02)
            finj.clear()
            if not victim.drain(timeout=30):
                errors.append("victim task group failed to drain")
            for f in vfuts:
                if not engine.skipped(f.result(timeout=10)):
                    errors.append("cancelled victim task actually ran")
                    break
    finally:
        finj.clear()
        soak_tokens = []
        for h in handles:
            try:
                soak_tokens.append(h.result(timeout=60))
            except Exception as e:
                errors.append(f"soak request {h.id} failed: {e!r}")
                soak_tokens.append(None)
        srv.wait(timeout=60)
        leaked_pages = srv.pool.in_use()
        srv.close()
        engine.wait_for_all()
        engine.set_aging_ms(prev_aging)

    # -- invariants ------------------------------------------------------
    if soak_tokens != clean_tokens:
        bad = [i for i, (a, b) in enumerate(zip(soak_tokens, clean_tokens))
               if a != b]
        errors.append(f"decode output not bitwise-stable under load: "
                      f"requests {bad} differ")
    if leaked_pages:
        errors.append(f"soak leaked {leaked_pages} KV pages")
    depth_after = depth_gauge.value or 0
    if depth_after != depth_before:
        errors.append(f"prefetch staging depth leaked: {depth_before} -> "
                      f"{depth_after}")
    live_groups = engine.active_groups()
    if live_groups:
        errors.append(f"{live_groups} task group(s) leaked live tasks")
    starved = [w for w in waits if w > STARVE_BOUND_S]
    if starved:
        errors.append(f"{len(starved)}/{len(waits)} decode-class turns "
                      f"starved past the aging bound {STARVE_BOUND_S}s "
                      f"(worst {max(starved):.3f}s)")
    if not waits:
        errors.append("soak measured no decode-class dispatch waits")
    if engine.debug_check():
        errors.append(f"race detector tripped during soak: "
                      f"{engine.last_error()}")
    # cancellation must be invisible to the failure report: the victim
    # fn is named, so any recorded entry naming it means a cancelled
    # task was (mis)counted as a failure
    if any("victim_task" in f["site"] for f in engine.failures()):
        errors.append("cancelled task recorded as an engine failure")
    engine.set_debug(False)
    engine.clear_error()
    waits.sort()
    p99 = waits[min(len(waits) - 1, int(0.99 * len(waits)))] if waits \
        else None
    return {
        "soak_requests": len(reqs),
        "soak_probe_turns": len(waits),
        "soak_starved_turns": len(starved),
        "starve_bound_s": STARVE_BOUND_S,
        "decode_dispatch_p99_s": round(p99, 4) if p99 is not None else None,
        "decode_dispatch_worst_s": round(waits[-1], 4) if waits else None,
        "soak_leaked_pages": leaked_pages,
        "soak_live_groups": live_groups,
        "serve_loop_restarts": registry().counter(
            "serve_loop_restarts").value,
    }


def run():
    errors = []
    res = {}
    res.update(_phase_fairness(errors))
    res.update(_phase_fifo_control(errors))
    res.update(_phase_soak(errors))
    res["errors"] = errors
    res["ok"] = not errors
    return res


def main(argv=None):
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    res = run()
    print(json.dumps(res))
    for err in res["errors"]:
        print(f"check_qos: {err}", file=sys.stderr)
    if res["errors"]:
        print("check_qos: FAIL", file=sys.stderr)
        return 1
    print(f"check_qos: OK ({res['soak_probe_turns']} decode-class turns, "
          f"0 starved past {res['starve_bound_s']}s, p99 "
          f"{res['decode_dispatch_p99_s']}s, FIFO control worst "
          f"{res['fifo_control_worst_wait_s']}s, 0 leaked pages/groups)",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
