"""mx.np — the numpy-compatible array front end (reference:
python/mxnet/numpy/, MXNet's "deepnumpy" from 1.6/2.0).

TPU-native design: there is no second dispatch path. `np.ndarray`
subclasses `mx.nd.NDArray`, and the single imperative dispatch point
(`ndarray._apply`) propagates np-ness — any op with an np input yields np
outputs. That one rule carries the numpy front end through every existing
kernel, every Gluon block (net(np_x) returns np arrays), and the autograd
tape, with zero duplicated op code. Functions here are thin numpy-named
adapters over `jnp`, so numpy semantics (broadcasting, dtype promotion,
0-d results, negative axes, boolean masks) come from XLA's own numpy
implementation rather than a reimplementation.

Divergences (SURVEY §8): float64 truncates to float32 (JAX x64 off, TPU
native dtypes); boolean-mask indexing and `nonzero`/`unique` are
eager-only (data-dependent shapes cannot live under jit — use `where`
inside compiled code).
"""
from __future__ import annotations

import numpy as _onp

import jax
import jax.numpy as jnp

from ..base import MXNetError, numeric_types
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import NDArray, _apply, _np_dtype

__all__ = ["ndarray", "array", "asarray", "zeros", "ones", "full", "empty",
           "arange", "linspace", "logspace", "eye", "identity",
           "zeros_like", "ones_like", "full_like", "empty_like",
           "concatenate", "stack", "vstack", "hstack", "dstack", "split",
           "expand_dims", "squeeze", "reshape", "transpose", "swapaxes",
           "moveaxis", "broadcast_to", "broadcast_arrays", "tile", "repeat",
           "flip", "roll", "where", "take", "take_along_axis", "sort",
           "argsort", "unique", "nonzero", "dot", "matmul", "tensordot",
           "einsum", "inner", "outer", "trace", "diag", "tril", "triu",
           "cross", "vander",
           "maximum", "minimum", "clip", "meshgrid", "atleast_1d",
           "atleast_2d", "atleast_3d", "pad", "cumsum", "cumprod",
           "append", "delete", "insert", "ravel",
           "may_share_memory", "shares_memory",
           "pi", "e", "inf", "nan", "newaxis", "random", "linalg"]


# --------------------------------------------------------------------- array
class ndarray(NDArray):
    """numpy-flavoured NDArray. Identical storage (a `jax.Array`); only the
    printed form and a few numpy-named members differ from nd."""

    def __repr__(self):
        return f"array({_onp.asarray(self._data)})"

    def __str__(self):
        return str(_onp.asarray(self._data))

    # numpy members not on the nd surface
    def item(self, *args):
        return _onp.asarray(self._data).item(*args)

    def tolist(self):
        return _onp.asarray(self._data).tolist()

    def std(self, axis=None, keepdims=False, ddof=0):
        return _apply(lambda a: jnp.std(a, axis=axis, ddof=ddof,
                                        keepdims=keepdims), [self])

    def var(self, axis=None, keepdims=False, ddof=0):
        return _apply(lambda a: jnp.var(a, axis=axis, ddof=ddof,
                                        keepdims=keepdims), [self])

    def all(self, axis=None, keepdims=False):
        return _apply(lambda a: jnp.all(a, axis=axis, keepdims=keepdims),
                      [self])

    def any(self, axis=None, keepdims=False):
        return _apply(lambda a: jnp.any(a, axis=axis, keepdims=keepdims),
                      [self])

    def cumsum(self, axis=None, dtype=None):
        return _apply(lambda a: jnp.cumsum(a, axis=axis, dtype=dtype), [self])

    def ravel(self):
        return _apply(jnp.ravel, [self])

    def nonzero(self):
        return tuple(ndarray(i) for i in jnp.nonzero(self._data))

    # numpy semantics: comparisons yield BOOL arrays (nd yields 0/1
    # floats for reference parity), so masks feed boolean indexing
    def __eq__(self, other):
        if other is None:   # numpy: x == None -> elementwise False
            return ndarray(jnp.zeros(self.shape, jnp.bool_))
        return _binary(jnp.equal)(self, other)

    def __ne__(self, other):
        if other is None:
            return ndarray(jnp.ones(self.shape, jnp.bool_))
        return _binary(jnp.not_equal)(self, other)

    def __lt__(self, other):
        return _binary(jnp.less)(self, other)

    def __le__(self, other):
        return _binary(jnp.less_equal)(self, other)

    def __gt__(self, other):
        return _binary(jnp.greater)(self, other)

    def __ge__(self, other):
        return _binary(jnp.greater_equal)(self, other)

    __hash__ = NDArray.__hash__  # defining __eq__ clears it otherwise

    @property
    def flat(self):
        return iter(self.reshape(-1))

    def as_nd_ndarray(self):
        """View as classic nd (shared buffer)."""
        return NDArray(self._data)

    def as_np_ndarray(self):
        return self


NDArray.as_np_ndarray = lambda self: ndarray(self._data)
_nd_mod._np_ndarray_cls = ndarray  # turn on np propagation in _apply


def _c(x, dtype=None):
    """Coerce to an np ndarray (shared buffer for NDArray inputs)."""
    if isinstance(x, ndarray):
        return x if dtype is None else x.astype(dtype)
    if isinstance(x, NDArray):
        out = ndarray(x._data)
        return out if dtype is None else out.astype(dtype)
    return array(x, dtype=dtype)


def array(obj, dtype=None, ctx=None):
    if isinstance(obj, NDArray):
        data = obj._data if dtype is None else obj._data.astype(
            _np_dtype(dtype))
        return ndarray(data, ctx=ctx)
    return ndarray(jnp.asarray(_onp.asarray(obj),
                               dtype=_np_dtype(dtype) if dtype else None),
                   ctx=ctx)


def asarray(obj, dtype=None):
    if isinstance(obj, ndarray) and dtype is None:
        return obj
    return array(obj, dtype=dtype)


# ------------------------------------------------------------------ factories
def _factory(jfn):
    def f(*args, dtype=None, ctx=None, **kw):
        kw.pop("order", None)
        if dtype is not None:
            kw["dtype"] = _np_dtype(dtype)
        return ndarray(jfn(*args, **kw), ctx=ctx)
    f.__name__ = jfn.__name__
    return f


zeros = _factory(jnp.zeros)
ones = _factory(jnp.ones)
full = _factory(jnp.full)
arange = _factory(jnp.arange)
linspace = _factory(jnp.linspace)
logspace = _factory(jnp.logspace)
eye = _factory(jnp.eye)
identity = _factory(jnp.identity)


def empty(shape, dtype=None, ctx=None):
    # XLA has no uninitialised-buffer primitive (SURVEY §8): zeros
    return zeros(shape, dtype=dtype or "float32", ctx=ctx)


def zeros_like(a, dtype=None):
    return _apply(lambda x: jnp.zeros_like(x, dtype=_np_dtype(dtype)
                                           if dtype else None), [_c(a)])


def ones_like(a, dtype=None):
    return _apply(lambda x: jnp.ones_like(x, dtype=_np_dtype(dtype)
                                          if dtype else None), [_c(a)])


def full_like(a, fill_value, dtype=None):
    return _apply(lambda x: jnp.full_like(x, fill_value,
                                          dtype=_np_dtype(dtype)
                                          if dtype else None), [_c(a)])


empty_like = zeros_like


# ------------------------------------------------------- generated math suite
def _unary(jfn):
    def f(x, **kw):
        kw.pop("out", None)
        return _apply(lambda a: jfn(a, **kw), [_c(x)])
    f.__name__ = jfn.__name__
    return f


def _binary(jfn):
    def f(x1, x2, **kw):
        kw.pop("out", None)
        a_nd, b_nd = isinstance(x1, NDArray), isinstance(x2, NDArray)
        if a_nd and b_nd:
            return _apply(lambda a, b: jfn(a, b, **kw), [_c(x1), _c(x2)])
        if a_nd:  # python scalars stay weakly typed (no silent upcast)
            return _apply(lambda a, _b=x2: jfn(a, _b, **kw), [_c(x1)])
        if b_nd:
            return _apply(lambda b, _a=x1: jfn(_a, b, **kw), [_c(x2)])
        return array(jfn(jnp.asarray(x1), jnp.asarray(x2), **kw))
    f.__name__ = jfn.__name__
    return f


_UNARY = ("negative positive absolute abs fabs sign rint floor ceil "
          "trunc sqrt cbrt square reciprocal exp expm1 exp2 log log2 log10 "
          "log1p sin cos tan arcsin arccos arctan sinh cosh tanh arcsinh "
          "arccosh arctanh degrees radians isnan isinf isfinite logical_not "
          "invert")
_BINARY = ("add subtract multiply divide true_divide mod remainder power "
           "float_power hypot arctan2 logaddexp copysign logical_and "
           "logical_or logical_xor equal not_equal less less_equal greater "
           "greater_equal fmax fmin bitwise_and bitwise_or bitwise_xor "
           "left_shift right_shift floor_divide")
for _name in _UNARY.split():
    globals()[_name] = _unary(getattr(jnp, _name))
    __all__.append(_name)
fix = _unary(jnp.trunc)   # numpy fix == round toward zero == trunc
fix.__name__ = "fix"
__all__.append("fix")
for _name in _BINARY.split():
    globals()[_name] = _binary(getattr(jnp, _name))
    __all__.append(_name)
maximum = _binary(jnp.maximum)
minimum = _binary(jnp.minimum)


def _reduction(jfn, name=None):
    def f(a, axis=None, dtype=None, keepdims=False, **kw):
        kw.pop("out", None)
        kwargs = dict(axis=axis, keepdims=keepdims, **kw)
        if dtype is not None:
            kwargs["dtype"] = _np_dtype(dtype)
        return _apply(lambda x: jfn(x, **kwargs), [_c(a)])
    f.__name__ = name or jfn.__name__
    return f


for _name in ("sum prod mean max min amax amin all any nanmax nanmin "
              "nansum nanmean median").split():
    globals()[_name] = _reduction(getattr(jnp, _name))
    __all__.append(_name)


def std(a, axis=None, keepdims=False, ddof=0):
    return _apply(lambda x: jnp.std(x, axis=axis, ddof=ddof,
                                    keepdims=keepdims), [_c(a)])


def var(a, axis=None, keepdims=False, ddof=0):
    return _apply(lambda x: jnp.var(x, axis=axis, ddof=ddof,
                                    keepdims=keepdims), [_c(a)])


def argmax(a, axis=None):
    return _apply(lambda x: jnp.argmax(x, axis=axis), [_c(a)])


def argmin(a, axis=None):
    return _apply(lambda x: jnp.argmin(x, axis=axis), [_c(a)])


def average(a, axis=None, weights=None):
    if weights is None:
        return mean(a, axis=axis)
    return _apply(lambda x, w: jnp.average(x, axis=axis, weights=w),
                  [_c(a), _c(weights)])


def cumsum(a, axis=None, dtype=None):
    return _apply(lambda x: jnp.cumsum(x, axis=axis, dtype=dtype), [_c(a)])


def cumprod(a, axis=None, dtype=None):
    return _apply(lambda x: jnp.cumprod(x, axis=axis, dtype=dtype), [_c(a)])


__all__ += ["std", "var", "argmax", "argmin", "average"]


# ----------------------------------------------------------------- shape ops
def reshape(a, newshape, order="C"):
    return _apply(lambda x: jnp.reshape(x, newshape), [_c(a)])


def transpose(a, axes=None):
    return _apply(lambda x: jnp.transpose(x, axes), [_c(a)])


def swapaxes(a, axis1, axis2):
    return _apply(lambda x: jnp.swapaxes(x, axis1, axis2), [_c(a)])


def moveaxis(a, source, destination):
    return _apply(lambda x: jnp.moveaxis(x, source, destination), [_c(a)])


def expand_dims(a, axis):
    return _apply(lambda x: jnp.expand_dims(x, axis), [_c(a)])


def squeeze(a, axis=None):
    return _apply(lambda x: jnp.squeeze(x, axis=axis), [_c(a)])


def ravel(a):
    return _apply(jnp.ravel, [_c(a)])


def broadcast_to(a, shape):
    return _apply(lambda x: jnp.broadcast_to(x, shape), [_c(a)])


def _as_list(res, n):
    """Multi-output _apply returns a bare ndarray when n==1 — wrap it
    (list(ndarray) would iterate rows, not make a 1-list)."""
    return [res] if n == 1 else list(res)


def broadcast_arrays(*arrays):
    n = len(arrays)
    return _as_list(_apply(lambda *xs: tuple(jnp.broadcast_arrays(*xs)),
                           [_c(a) for a in arrays], n_out=n), n)


def tile(a, reps):
    return _apply(lambda x: jnp.tile(x, reps), [_c(a)])


def repeat(a, repeats, axis=None):
    return _apply(lambda x: jnp.repeat(x, repeats, axis=axis), [_c(a)])


def flip(a, axis=None):
    return _apply(lambda x: jnp.flip(x, axis=axis), [_c(a)])


def roll(a, shift, axis=None):
    return _apply(lambda x: jnp.roll(x, shift, axis=axis), [_c(a)])


def pad(a, pad_width, mode="constant", **kw):
    return _apply(lambda x: jnp.pad(x, pad_width, mode=mode, **kw), [_c(a)])


def concatenate(seq, axis=0):
    return _apply(lambda *xs: jnp.concatenate(xs, axis=axis),
                  [_c(a) for a in seq])


def stack(seq, axis=0):
    return _apply(lambda *xs: jnp.stack(xs, axis=axis),
                  [_c(a) for a in seq])


def vstack(seq):
    return _apply(lambda *xs: jnp.vstack(xs), [_c(a) for a in seq])


def hstack(seq):
    return _apply(lambda *xs: jnp.hstack(xs), [_c(a) for a in seq])


def dstack(seq):
    return _apply(lambda *xs: jnp.dstack(xs), [_c(a) for a in seq])


def split(a, indices_or_sections, axis=0):
    a = _c(a)
    n = indices_or_sections if isinstance(indices_or_sections, int) \
        else len(indices_or_sections) + 1
    return _as_list(_apply(lambda x: tuple(jnp.split(
        x, indices_or_sections, axis=axis)), [a], n_out=n), n)


def atleast_1d(*arys):
    outs = [_apply(jnp.atleast_1d, [_c(a)]) for a in arys]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*arys):
    outs = [_apply(jnp.atleast_2d, [_c(a)]) for a in arys]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*arys):
    outs = [_apply(jnp.atleast_3d, [_c(a)]) for a in arys]
    return outs[0] if len(outs) == 1 else outs


def append(arr, values, axis=None):
    return _apply(lambda a, v: jnp.append(a, v, axis=axis),
                  [_c(arr), _c(values)])


def delete(arr, obj, axis=None):
    return _apply(lambda a: jnp.delete(a, obj, axis=axis), [_c(arr)])


def insert(arr, obj, values, axis=None):
    return _apply(lambda a, v: jnp.insert(a, obj, v, axis=axis),
                  [_c(arr), _c(values)])


def meshgrid(*xi, indexing="xy"):
    # NB: builtins max/min/sum/all/any are shadowed by the reductions
    # defined above — module code must not call them bare
    n = len(xi) or 1
    return _as_list(_apply(lambda *xs: tuple(jnp.meshgrid(
        *xs, indexing=indexing)), [_c(x) for x in xi], n_out=n), n)


# ----------------------------------------------------------- linalg-ish ops
def dot(a, b):
    return _apply(jnp.dot, [_c(a), _c(b)])


def matmul(a, b):
    return _apply(jnp.matmul, [_c(a), _c(b)])


def cross(a, b, axis=-1):
    return _apply(lambda x, y: jnp.cross(x, y, axis=axis),
                  [_c(a), _c(b)])


def vander(x, N=None, increasing=False):
    return _apply(lambda v: jnp.vander(v, N=N, increasing=increasing),
                  [_c(x)])


def tensordot(a, b, axes=2):
    return _apply(lambda x, y: jnp.tensordot(x, y, axes=axes),
                  [_c(a), _c(b)])


def einsum(subscripts, *operands):
    return _apply(lambda *xs: jnp.einsum(subscripts, *xs),
                  [_c(o) for o in operands])


def inner(a, b):
    return _apply(jnp.inner, [_c(a), _c(b)])


def outer(a, b):
    return _apply(jnp.outer, [_c(a), _c(b)])


def trace(a, offset=0, axis1=0, axis2=1):
    return _apply(lambda x: jnp.trace(x, offset=offset, axis1=axis1,
                                      axis2=axis2), [_c(a)])


def diag(v, k=0):
    return _apply(lambda x: jnp.diag(x, k=k), [_c(v)])


def tril(m, k=0):
    return _apply(lambda x: jnp.tril(x, k=k), [_c(m)])


def triu(m, k=0):
    return _apply(lambda x: jnp.triu(x, k=k), [_c(m)])


# ------------------------------------------------------- select and indexing
def where(condition, x=None, y=None):
    if x is None and y is None:
        return nonzero(condition)
    return _apply(lambda c, a, b: jnp.where(c, a, b),
                  [_c(condition), _c(x), _c(y)])


def take(a, indices, axis=None, mode="clip"):
    return _apply(lambda x, i: jnp.take(x, i.astype(jnp.int32), axis=axis,
                                        mode=mode),
                  [_c(a), _c(indices)])


def take_along_axis(a, indices, axis):
    return _apply(lambda x, i: jnp.take_along_axis(
        x, i.astype(jnp.int32), axis=axis), [_c(a), _c(indices)])


def sort(a, axis=-1):
    return _apply(lambda x: jnp.sort(x, axis=axis), [_c(a)])


def argsort(a, axis=-1):
    return _apply(lambda x: jnp.argsort(x, axis=axis), [_c(a)])


def clip(a, a_min=None, a_max=None):
    return _apply(lambda x: jnp.clip(x, a_min, a_max), [_c(a)])


def unique(ar, return_index=False, return_inverse=False,
           return_counts=False):
    """Eager-only (data-dependent output shape — SURVEY §8)."""
    res = jnp.unique(_c(ar)._data, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts)
    if isinstance(res, tuple):
        return tuple(ndarray(r) for r in res)
    return ndarray(res)


def nonzero(a):
    """Eager-only (data-dependent output shape — SURVEY §8)."""
    return tuple(ndarray(i) for i in jnp.nonzero(_c(a)._data))


def histogram(a, bins=10, range=None):
    return _apply(lambda x: tuple(jnp.histogram(x, bins=bins,
                                                range=range)),
                  [_c(a)], n_out=2)


def bincount(a, weights=None, minlength=0):
    """Eager-only when minlength doesn't cover the data (output length
    is data-dependent — SURVEY §8)."""
    from ..ops.compat_ops import bincount as _bc
    return _bc(_c(a), weights=None if weights is None else _c(weights),
               minlength=minlength)


def percentile(a, q, axis=None, keepdims=False):
    return _apply(lambda x: jnp.percentile(x, q, axis=axis,
                                           keepdims=keepdims), [_c(a)])


def quantile(a, q, axis=None, keepdims=False):
    return _apply(lambda x: jnp.quantile(x, q, axis=axis,
                                         keepdims=keepdims), [_c(a)])


def digitize(x, bins, right=False):
    return _apply(lambda a, b: jnp.digitize(a, b, right=right),
                  [_c(x), _c(bins)])


def searchsorted(a, v, side="left"):
    return _apply(lambda x, q: jnp.searchsorted(x, q, side=side),
                  [_c(a), _c(v)])


def count_nonzero(a, axis=None, keepdims=False):
    return _apply(lambda x: jnp.count_nonzero(x, axis=axis,
                                              keepdims=keepdims), [_c(a)])


def argwhere(a):
    """Eager-only (data-dependent shape — SURVEY §8)."""
    return ndarray(jnp.argwhere(_c(a)._data))


def flatnonzero(a):
    """Eager-only (data-dependent shape — SURVEY §8)."""
    return ndarray(jnp.flatnonzero(_c(a)._data))


def interp(x, xp, fp):
    return _apply(lambda a, b, c: jnp.interp(a, b, c),
                  [_c(x), _c(xp), _c(fp)])


__all__ += ["histogram", "bincount", "percentile", "quantile", "digitize",
            "searchsorted", "count_nonzero", "argwhere", "flatnonzero",
            "interp"]


def isclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return _apply(lambda x, y: jnp.isclose(x, y, rtol=rtol, atol=atol,
                                           equal_nan=equal_nan),
                  [_c(a), _c(b)])


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    return bool(jnp.allclose(_c(a)._data, _c(b)._data, rtol=rtol,
                             atol=atol, equal_nan=equal_nan))


def array_equal(a1, a2):
    return bool(jnp.array_equal(_c(a1)._data, _c(a2)._data))


def may_share_memory(a, b, max_work=None):
    # jax.Arrays are immutable; buffer identity is the only sharing
    return isinstance(a, NDArray) and isinstance(b, NDArray) \
        and a._data is b._data


shares_memory = may_share_memory
__all__ += ["isclose", "allclose", "array_equal"]

# ------------------------------------------------------------------ constants
pi = _onp.pi
e = _onp.e
inf = _onp.inf
nan = _onp.nan
newaxis = None
# dtype names, numpy-style
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
bool_ = _onp.bool_
__all__ += ["float16", "float32", "float64", "int8", "int16", "int32",
            "int64", "uint8", "bool_"]

from . import random     # noqa: E402  (needs ndarray defined)
from . import linalg     # noqa: E402
