"""Neural-network ops (reference: src/operator/nn/*).

Two layers:
  * pure kernels over `jax.Array` (suffix-free lowercase functions) — these
    are what Gluon layers call inside `hybrid_forward`, so a hybridized net
    traces into one XLA executable. Convs ride `lax.conv_general_dilated`
    (MXU), layouts are configurable (reference default NCHW accepted; NHWC is
    the TPU-preferred fast path used by the model zoo's `layout` option).
  * imperative NDArray wrappers with the reference's legacy op names
    (FullyConnected, Convolution, BatchNorm, Pooling, Activation, Dropout,
    SoftmaxOutput, ...) dispatched through `_apply` so autograd records them.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _apply, _lift

__all__ = [
    "fully_connected", "convolution", "deconvolution", "stem_conv_s2d",
    "StemConvS2D", "batch_norm",
    "layer_norm", "group_norm", "instance_norm", "pooling", "global_pooling",
    "activation", "leaky_relu", "dropout", "embedding", "softmax",
    "log_softmax", "softmax_cross_entropy", "rnn_step",
    "FullyConnected", "Convolution", "Deconvolution", "BatchNorm", "LayerNorm",
    "InstanceNorm", "GroupNorm", "PReLU",
    "Pooling", "Activation", "LeakyReLU", "Dropout", "Embedding",
    "SoftmaxOutput",
    "softmax_nd", "log_softmax_nd", "relu", "sigmoid", "gelu", "silu",
    "Pooling_v1", "Convolution_v1",
]


# ---------------------------------------------------------------------------
# pure kernels (jax.Array -> jax.Array)
# ---------------------------------------------------------------------------
def _amp_cast(x, weight):
    """Op-level AMP autocast (amp.init()): fp32 matmul/conv operands run on
    the MXU in the AMP target dtype. EITHER side being fp32 is downcast —
    a bf16 activation meeting an fp32 master weight must not promote the
    dot back to fp32. Applied at trace time; no-op when AMP is off."""
    from ..amp import autocast_dtype
    dt = autocast_dtype()
    if dt is None:
        return x, weight
    if x.dtype == jnp.float32:
        x = x.astype(dt)
    if weight.dtype == jnp.float32:
        weight = weight.astype(dt)
    return x, weight


def fully_connected(x, weight, bias=None, flatten=True):
    """y = x @ W^T + b. weight: (num_hidden, in_units) — reference convention
    (src/operator/nn/fully_connected.cc)."""
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    x, weight = _amp_cast(x, weight)
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def _conv_dn(ndim, layout):
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
    spatial = layout.replace("N", "").replace("C", "")
    rhs = "OI" + spatial  # weight layout (out_ch, in_ch, *kernel)
    return layout, lax.conv_dimension_numbers(
        (1,) * (ndim + 2), (1,) * (ndim + 2), (layout, rhs, layout))


def convolution(x, weight, bias=None, stride=1, pad=0, dilate=1,
                num_group=1, layout=None):
    """N-d convolution on the MXU. weight layout (O, I/g, *k) for NC* layouts
    or (O, *k, I/g) for N*C layouts (reference: conv layout semantics)."""
    ndim = x.ndim - 2
    if isinstance(stride, int):
        stride = (stride,) * ndim
    if isinstance(pad, int):
        pad = (pad,) * ndim
    if isinstance(dilate, int):
        dilate = (dilate,) * ndim
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
    spatial = layout.replace("N", "").replace("C", "")
    rhs = ("OI" + spatial) if layout.index("C") == 1 else ("O" + spatial + "I")
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (layout, rhs, layout))
    x, weight = _amp_cast(x, weight)
    # bf16 in / bf16 out: the TPU MXU accumulates in fp32 internally, and a
    # preferred_element_type upcast would poison the conv transpose (the AD
    # rule requires cotangent dtype == primal dtype). fp32 master weights
    # compute in the activation dtype; the astype transpose returns the
    # weight cotangent in fp32 (the multi-precision optimizer pattern).
    if weight.dtype != x.dtype:
        weight = weight.astype(x.dtype)
    y = lax.conv_general_dilated(
        x, weight, window_strides=tuple(stride),
        padding=tuple((p, p) for p in pad),
        rhs_dilation=tuple(dilate), dimension_numbers=dn,
        feature_group_count=num_group)
    if bias is not None:
        c_axis = layout.index("C")
        shape = [1] * y.ndim
        shape[c_axis] = -1
        y = y + bias.reshape(shape).astype(y.dtype)
    return y


def stem_conv_s2d(x, weight):
    """7x7/stride-2/pad-3 NHWC convolution computed via space-to-depth.

    Mathematically identical to `convolution(x, weight, stride=2, pad=3,
    layout="NHWC")` for a (O, 7, 7, C) weight, but the conv runs on the
    (H/2, W/2, 4C) space-to-depth input with a (O, 4, 4, 4C) repacked kernel,
    stride 1, asymmetric pad (2, 1). A 3-channel stride-2 conv tiles terribly
    onto the MXU (its weight gradient ran at <5% efficiency in profiles);
    4x the input channels and stride 1 fix the tiling. This is the standard
    TPU ResNet stem optimisation (MLPerf space-to-depth trick).
    """
    n, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            f"stem_conv_s2d needs even spatial dims, got {(h, w)}; use "
            "convolution(..., stride=2, pad=3) for odd sizes")
    o = weight.shape[0]
    xs = x.reshape(n, h // 2, 2, w // 2, 2, c)
    xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2, 4 * c)
    # repack: w2[o, ka, kb, (p*2+q)*C + c] = w[o, u, v, c] with
    # u = 2*ka + p - 4 + 3, i.e. grid index u+1 in an 8-wide padded kernel
    wp = jnp.pad(weight, ((0, 0), (1, 0), (1, 0), (0, 0)))       # (O,8,8,C)
    w2 = wp.reshape(o, 4, 2, 4, 2, c).transpose(0, 1, 3, 2, 4, 5)
    w2 = w2.reshape(o, 4, 4, 4 * c)
    dn = lax.conv_dimension_numbers(xs.shape, w2.shape,
                                    ("NHWC", "OHWI", "NHWC"))
    return lax.conv_general_dilated(
        xs, w2.astype(xs.dtype), window_strides=(1, 1),
        padding=((2, 1), (2, 1)), dimension_numbers=dn)


def deconvolution(x, weight, bias=None, stride=1, pad=0, adj=0, layout=None):
    """Transposed convolution (reference: deconvolution.cc). weight (I, O, *k)."""
    ndim = x.ndim - 2
    if isinstance(stride, int):
        stride = (stride,) * ndim
    if isinstance(pad, int):
        pad = (pad,) * ndim
    if isinstance(adj, int):
        adj = (adj,) * ndim
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
    spatial = layout.replace("N", "").replace("C", "")
    rhs = "IO" + spatial
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (layout, rhs, layout))
    k = weight.shape[2:]
    padding = tuple((d - 1 - p, d - 1 - p + a) for d, p, a in
                    zip(k, pad, adj))
    # gradient formulation of transposed conv: dilate the input by `stride`
    # and convolve with the spatially-flipped kernel (out = (in-1)*s - 2p +
    # k + adj, reference deconvolution.cc semantics)
    flipped = lax.rev(weight, tuple(range(2, weight.ndim)))
    y = lax.conv_general_dilated(
        x, flipped, window_strides=(1,) * ndim, padding=padding,
        lhs_dilation=tuple(stride), dimension_numbers=dn)
    if bias is not None:
        c_axis = layout.index("C")
        shape = [1] * y.ndim
        shape[c_axis] = -1
        y = y + bias.reshape(shape).astype(y.dtype)
    return y


@partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_train(x, gamma, beta, shift, axis, eps):
    """Training-mode BN core with a hand-fused backward.

    Forward is two memory passes: one fused multi-output reduction computing
    E[x] and E[x^2] in fp32 (single read of x), one elementwise apply.
    Backward is two more: one fused reduction for (dbeta, dgamma), one
    elementwise pass for dx — the minimum for BN training. Autodiff of the
    naive two-stage mean/var formulation costs ~2x more passes, which
    profiling showed dominating the ResNet-50 step (BN reduce fusions were
    44% of device time). The stat outputs (batch mean/var, fp32) feed the
    moving-average update only and are treated as stop_gradient, matching
    the reference where running stats are non-differentiable aux states
    (src/operator/nn/batch_norm.cc).
    """
    y, mean, var, _inv = _bn_train_fwd_impl(x, gamma, beta, shift, axis, eps)
    return y, mean, var


def _bn_train_fwd_impl(x, gamma, beta, shift, axis, eps):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = -1
    xf = x.astype(jnp.float32)
    # shifted one-pass moments: E[x^2]-E[x]^2 on raw values loses all fp32
    # precision when |mean| >> std (training diverged within steps once
    # activations drifted). Shifting by the running mean — an independent
    # input, so both reduces still fuse into ONE pass over x — keeps the
    # cancellation at O(eps * (std^2 + lag^2)) where lag = |E[x] - shift|,
    # benign since the running mean tracks the batch mean.
    sf = lax.stop_gradient(shift.astype(jnp.float32)).reshape(shape)
    xc = xf - sf
    m1 = jnp.mean(xc, axis=axes)
    var = jnp.maximum(jnp.mean(xc * xc, axis=axes) - m1 * m1, 0.0)
    mean = m1 + sf.reshape(-1)
    inv = lax.rsqrt(var + eps)
    gf = gamma.astype(jnp.float32).reshape(shape)
    bf = beta.astype(jnp.float32).reshape(shape)
    y = ((xf - mean.reshape(shape)) * inv.reshape(shape) * gf + bf)
    return y.astype(x.dtype), mean, var, inv


def _bn_train_vjp_fwd(x, gamma, beta, shift, axis, eps):
    y, mean, var, inv = _bn_train_fwd_impl(x, gamma, beta, shift, axis, eps)
    return (y, mean, var), (x, gamma, mean, inv, shift)


def _bn_train_vjp_bwd(axis, eps, res, cots):
    dy, _dmean, _dvar = cots   # stat outputs: aux tracking only, no grad
    x, gamma, mean, inv, shift = res
    axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = -1
    n = 1
    for i in axes:
        n *= x.shape[i]
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    dbeta = jnp.sum(dyf, axis=axes)                  # fused with dgamma:
    dgamma = jnp.sum(dyf * xhat, axis=axes)          # one pass over (x, dy)
    k = (gamma.astype(jnp.float32) * inv / n).reshape(shape)
    dx = k * (n * dyf - dbeta.reshape(shape) - xhat * dgamma.reshape(shape))
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype), jnp.zeros_like(shift))


_bn_train.defvjp(_bn_train_vjp_fwd, _bn_train_vjp_bwd)


def batch_norm(x, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, training=True, axis=1):
    """BatchNorm. Returns (y, new_moving_mean, new_moving_var)."""
    if training:
        y, mean, var = _bn_train(x, gamma, beta, moving_mean, axis,
                                 float(eps))
        new_mean = (momentum * moving_mean.astype(jnp.float32)
                    + (1 - momentum) * mean).astype(moving_mean.dtype)
        new_var = (momentum * moving_var.astype(jnp.float32)
                   + (1 - momentum) * var).astype(moving_var.dtype)
        return y, new_mean, new_var
    shape = [1] * x.ndim
    shape[axis] = -1
    inv = lax.rsqrt(moving_var.astype(jnp.float32) + eps)
    scale = (gamma.astype(jnp.float32) * inv).reshape(shape)
    shift = (beta.astype(jnp.float32)
             - gamma.astype(jnp.float32) * moving_mean.astype(jnp.float32)
             * inv).reshape(shape)
    y = (x.astype(jnp.float32) * scale + shift).astype(x.dtype)
    return y, moving_mean, moving_var


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[axis] = -1
    return y * gamma.reshape(shape) + beta.reshape(shape)


def group_norm(x, gamma, beta, num_groups, eps=1e-5):
    """GroupNorm over channel-first (N, C, ...) layout."""
    n, c = x.shape[0], x.shape[1]
    orig = x.shape
    xg = x.reshape(n, num_groups, c // num_groups, -1)
    mean = jnp.mean(xg, axis=(2, 3), keepdims=True)
    var = jnp.var(xg, axis=(2, 3), keepdims=True)
    xg = (xg - mean) * lax.rsqrt(var + eps)
    y = xg.reshape(orig)
    shape = [1] * x.ndim
    shape[1] = -1
    return y * gamma.reshape(shape) + beta.reshape(shape)


def instance_norm(x, gamma, beta, eps=1e-5):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[1] = -1
    return y * gamma.reshape(shape) + beta.reshape(shape)


def pooling(x, kernel, pool_type="max", stride=None, pad=0, layout=None,
            count_include_pad=True):
    """Max/avg/sum pooling via lax.reduce_window."""
    ndim = x.ndim - 2
    if isinstance(kernel, int):
        kernel = (kernel,) * ndim
    stride = stride or kernel
    if isinstance(stride, int):
        stride = (stride,) * ndim
    if isinstance(pad, int):
        pad = (pad,) * ndim
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
    c_axis = layout.index("C")
    window = [1] * x.ndim
    strides = [1] * x.ndim
    paddings = [(0, 0)] * x.ndim
    sp = [i for i in range(x.ndim) if i not in (0, c_axis)]
    for i, ax in enumerate(sp):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        paddings[ax] = (pad[i], pad[i])
    if pool_type == "max":
        # init must be a python scalar: an array-valued init defeats XLA's
        # monoid recognition and kills the reduce_window VJP on TPU
        if jnp.issubdtype(x.dtype, jnp.floating):
            init = -jnp.inf
        else:
            init = int(jnp.iinfo(x.dtype).min)
        return lax.reduce_window(x, init, lax.max,
                                 tuple(window), tuple(strides), tuple(paddings))
    zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
    s = lax.reduce_window(x, zero, lax.add,
                          tuple(window), tuple(strides), tuple(paddings))
    if pool_type == "sum":
        return s
    if count_include_pad:
        denom = 1
        for k in kernel:
            denom *= k
        return s / denom
    ones = jnp.ones_like(x)
    cnt = lax.reduce_window(ones, zero, lax.add,
                            tuple(window), tuple(strides), tuple(paddings))
    return s / cnt


def global_pooling(x, pool_type="avg", layout="NCHW", keepdims=True):
    c_axis = layout.index("C")
    axes = tuple(i for i in range(x.ndim) if i not in (0, c_axis))
    if pool_type == "max":
        return jnp.max(x, axis=axes, keepdims=keepdims)
    return jnp.mean(x, axis=axes, keepdims=keepdims)


_ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "gelu": jax.nn.gelu,
    "erf_gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "mish": jax.nn.mish,
    "relu6": lambda x: jnp.clip(x, 0, 6),
    # MXNet semantics: clip(0.2*x + 0.5, 0, 1) — NOT jax.nn.hard_sigmoid's
    # 1/6 slope; must match nd.hard_sigmoid (ops/seq_ops.py)
    "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
    "hard_swish": jax.nn.hard_swish,
    "exp": jnp.exp,
    "identity": lambda x: x,
}


def activation(x, act_type="relu"):
    return _ACTS[act_type](x)


def leaky_relu(x, act_type="leaky", slope=0.25, alpha=None):
    if act_type in ("leaky", "prelu"):
        a = slope if alpha is None else alpha
        return jnp.where(x >= 0, x, a * x)
    if act_type == "elu":
        return jnp.where(x >= 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x)
    raise ValueError(f"unknown leaky_relu act_type {act_type}")


def dropout(x, key, p=0.5, training=True):
    if not training or p <= 0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


def embedding(indices, weight):
    # integer index batches pass through UNTOUCHED (int32/int64): the old
    # unconditional astype(int32) round-tripped nothing through float,
    # but ISSUE 15 pins the contract — only non-integer indices (the
    # MXNet float-default compat path) are cast, and that cast is lossy
    # above 2**24 rows (recommender scale wants a ShardedEmbedding with
    # integer inputs, which refuses floats outright)
    if not jnp.issubdtype(indices.dtype, jnp.integer):
        indices = indices.astype(jnp.int32)
    return jnp.take(weight, indices, axis=0)


def softmax(x, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def softmax_cross_entropy(logits, labels, sparse=True, axis=-1):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if sparse:
        lab = labels.astype(jnp.int32)
        return -jnp.take_along_axis(logp, lab[..., None], axis=axis)[..., 0]
    return -jnp.sum(labels * logp, axis=axis)


def rnn_step(x, h, wx, wh, b, mode="rnn_tanh"):
    g = jnp.matmul(x, wx.T) + jnp.matmul(h, wh.T) + b
    if mode == "rnn_tanh":
        return jnp.tanh(g)
    if mode == "rnn_relu":
        return jax.nn.relu(g)
    raise ValueError(mode)


# ---------------------------------------------------------------------------
# imperative NDArray wrappers (reference legacy op names)
# ---------------------------------------------------------------------------
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True, **kwargs):
    ins = [data, weight] + ([] if no_bias or bias is None else [bias])
    if no_bias or bias is None:
        return _apply(lambda x, w, _f=flatten: fully_connected(x, w, None, _f), ins)
    return _apply(lambda x, w, b, _f=flatten: fully_connected(x, w, b, _f), ins)


def Convolution(data, weight, bias=None, kernel=None, stride=1, pad=0,
                dilate=1, num_filter=None, num_group=1, no_bias=False,
                layout=None, **kwargs):
    if no_bias or bias is None:
        return _apply(lambda x, w, _s=stride, _p=pad, _d=dilate, _g=num_group,
                      _l=layout: convolution(x, w, None, _s, _p, _d, _g, _l),
                      [data, weight])
    return _apply(lambda x, w, b, _s=stride, _p=pad, _d=dilate, _g=num_group,
                  _l=layout: convolution(x, w, b, _s, _p, _d, _g, _l),
                  [data, weight, bias])


def StemConvS2D(data, weight, **kwargs):
    """NDArray wrapper for `stem_conv_s2d` (7x7/s2/p3 NHWC stem conv)."""
    return _apply(stem_conv_s2d, [data, weight])


def Deconvolution(data, weight, bias=None, kernel=None, stride=1, pad=0,
                  adj=0, num_filter=None, no_bias=False, layout=None, **kwargs):
    if no_bias or bias is None:
        return _apply(lambda x, w, _s=stride, _p=pad, _a=adj, _l=layout:
                      deconvolution(x, w, None, _s, _p, _a, _l), [data, weight])
    return _apply(lambda x, w, b, _s=stride, _p=pad, _a=adj, _l=layout:
                  deconvolution(x, w, b, _s, _p, _a, _l), [data, weight, bias])


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              axis=1, **kwargs):
    from .. import autograd
    training = autograd.is_training() and not use_global_stats
    out, new_mean, new_var = _apply(
        lambda x, g, b, mm, mv, _e=eps, _m=momentum, _t=training, _ax=axis:
        batch_norm(x, jnp.ones_like(g) if fix_gamma else g, b, mm, mv,
                   _e, _m, _t, _ax),
        [data, gamma, beta, moving_mean, moving_var], n_out=3)
    if training:
        # reference semantics: aux states are mutated in place during training
        moving_mean._assign_value(new_mean._data)
        moving_var._assign_value(new_var._data)
    return out


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, **kwargs):
    return _apply(lambda x, g, b, _ax=axis, _e=eps: layer_norm(x, g, b, _ax, _e),
                  [data, gamma, beta])


def prelu(x, alpha):
    """PReLU with shared or per-channel alpha (reference: leaky_relu-inl.h
    act_type='prelu')."""
    if x.ndim > 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x >= 0, x, alpha * x)


def InstanceNorm(data, gamma, beta, eps=1e-5, **kwargs):
    return _apply(lambda x, g, b, _e=eps: instance_norm(x, g, b, _e),
                  [data, gamma, beta])


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **kwargs):
    return _apply(lambda x, g, b, _n=num_groups, _e=eps:
                  group_norm(x, g, b, _n, _e), [data, gamma, beta])


def PReLU(data, alpha, **kwargs):
    return _apply(prelu, [data, alpha])


def Pooling(data, kernel=None, pool_type="max", stride=None, pad=0,
            global_pool=False, layout=None, **kwargs):
    if global_pool:
        return _apply(lambda x, _pt=pool_type, _l=layout or "NCHW":
                      global_pooling(x, _pt, _l), [data])
    return _apply(lambda x, _k=kernel, _pt=pool_type, _s=stride, _p=pad,
                  _l=layout: pooling(x, _k, _pt, _s, _p, _l), [data])


def Activation(data, act_type="relu", **kwargs):
    return _apply(lambda x, _a=act_type: activation(x, _a), [data])


def LeakyReLU(data, act_type="leaky", slope=0.25, **kwargs):
    return _apply(lambda x, _a=act_type, _s=slope: leaky_relu(x, _a, _s), [data])


def Dropout(data, p=0.5, mode="training", **kwargs):
    from .. import autograd
    from ..random import _next_key
    if not autograd.is_training() and mode != "always":
        return data
    key = _next_key()
    return _apply(lambda x, _k=key, _p=p: dropout(x, _k, _p, True), [data])


def Embedding(data, weight, input_dim=None, output_dim=None, **kwargs):
    return _apply(lambda i, w: embedding(i, w), [data, weight])


def SoftmaxOutput(data, label=None, **kwargs):
    return _apply(lambda x: jax.nn.softmax(x, axis=-1), [data])


def softmax_nd(data, length=None, axis=-1, temperature=None,
               use_length=False, causal=False):
    # positional order matches the reference AND the symbol-side softmax:
    # (data, length, axis, ...) — python/mxnet/ndarray/gen_op softmax
    # reference: softmax(..., use_length=True) masks positions >= the
    # per-batch length along the (last) softmax axis (src/operator/nn/
    # softmax.cc); `causal` (attention-export extension) masks positions
    # past the query row. Same kernel the symbol op and ONNX export pin.
    if length is not None or use_length or causal:
        if use_length and length is None:
            raise MXNetError("softmax: use_length=True needs a length input")

        def masked(x, *maybe_ln, _ax=axis, _t=temperature):
            if _t is not None and _t != 1.0:
                x = x / _t
            if _ax % x.ndim != x.ndim - 1:
                raise MXNetError(
                    "softmax: masking supports the last axis only")
            keep = jnp.ones((), bool)
            idx = jnp.arange(x.shape[-1])
            if maybe_ln:
                lb = maybe_ln[0].astype(jnp.int32).reshape(
                    (maybe_ln[0].shape[0],) + (1,) * (x.ndim - 1))
                keep = keep & (idx < lb)
            if causal:
                keep = keep & (idx[None, :] <= jnp.arange(
                    x.shape[-2])[:, None])
            return jax.nn.softmax(jnp.where(keep, x, -1e9), axis=-1)

        ins = [data] + ([length] if length is not None else [])
        return _apply(masked, ins)
    return _apply(lambda x, _ax=axis, _t=temperature: softmax(x, _ax, _t), [data])


def log_softmax_nd(data, axis=-1):
    return _apply(lambda x, _ax=axis: log_softmax(x, _ax), [data])


def relu(data):
    return _apply(jax.nn.relu, [data])


def sigmoid(data):
    return _apply(jax.nn.sigmoid, [data])


def gelu(data):
    return _apply(jax.nn.gelu, [data])


def silu(data):
    return _apply(jax.nn.silu, [data])


# legacy _v1 spellings (reference: pooling_v1.cc, convolution_v1.cc —
# identical semantics; upstream kept both op names registered)
Pooling_v1 = Pooling
Convolution_v1 = Convolution
