"""mx.rnn — bucketing IO for variable-length sequence training
(reference: python/mxnet/rnn/io.py).

BucketSentenceIter sorts sentences into length buckets and yields padded
batches tagged with `bucket_key`, the routing key BucketingModule uses to
pick the per-bucket compiled Executor. On TPU a bucket IS a compile-cache
entry (XLA needs static shapes), so bucketing is the idiomatic
variable-length strategy — a handful of executables instead of one per
length.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter:
    """Bucketed language-model iterator: for each sentence the label is the
    input shifted left by one (next-token prediction), padded with
    `invalid_label` to the bucket length.

    sentences: list of lists of int token ids.
    buckets: sorted bucket lengths; defaults to the lengths present.
    """

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT", seed=0):
        if buckets is None:
            lengths = {len(s) for s in sentences if len(s) >= 2}
            buckets = sorted(lengths)
        self.buckets = sorted(buckets)
        if not self.buckets:
            raise MXNetError("no buckets (need sentences of length >= 2)")
        self.batch_size = batch_size
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        if layout not in ("NT", "TN"):
            raise MXNetError(f"layout must be NT or TN, got {layout!r}")
        self._layout = layout
        self._dtype = np.dtype(dtype)
        self._rng = np.random.RandomState(seed)

        self._data = [[] for _ in self.buckets]
        skipped = 0
        for s in sentences:
            idx = np.searchsorted(self.buckets, len(s))
            if idx == len(self.buckets) or len(s) < 2:
                skipped += 1  # longer than the largest bucket, or trivial
                continue
            buf = np.full(self.buckets[idx], invalid_label, np.int32)
            buf[:len(s)] = s
            self._data[idx].append(buf)
        self.skipped = skipped
        self._data = [np.asarray(b, np.int32).reshape(-1, blen)
                      for b, blen in zip(self._data, self.buckets)]
        self.default_bucket_key = max(self.buckets)
        self.reset()

    def _shape(self, blen):
        return ((self.batch_size, blen) if self._layout == "NT"
                else (blen, self.batch_size))

    # providers describe the DEFAULT bucket (reference behaviour); each
    # DataBatch carries its own bucket-sized descs
    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         self._shape(self.default_bucket_key))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         self._shape(self.default_bucket_key))]

    def reset(self):
        self._plan = []  # (bucket_idx, start) per batch
        for i, arr in enumerate(self._data):
            if len(arr) == 0:
                continue
            order = self._rng.permutation(len(arr))
            self._data[i] = arr[order]
            for start in range(0, len(arr) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, start))
        self._rng.shuffle(self._plan)
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        from ..ndarray.ndarray import array
        if self._cursor >= len(self._plan):
            raise StopIteration
        bidx, start = self._plan[self._cursor]
        self._cursor += 1
        blen = self.buckets[bidx]
        chunk = self._data[bidx][start:start + self.batch_size]
        data = chunk.astype(self._dtype)
        label = np.full_like(data, self.invalid_label)
        label[:, :-1] = data[:, 1:]  # next-token target
        if self._layout == "TN":
            data, label = data.T, label.T
        return DataBatch(
            data=[array(data)], label=[array(label)], bucket_key=blen,
            provide_data=[DataDesc(self.data_name, self._shape(blen))],
            provide_label=[DataDesc(self.label_name, self._shape(blen))])

    next = __next__
