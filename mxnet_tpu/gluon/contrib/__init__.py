"""gluon.contrib (reference: python/mxnet/gluon/contrib).

Experimental-tier Gluon layers: cross-replica SyncBatchNorm, pixel shuffle,
convolutional and variational-dropout RNN cells.
"""
from . import nn
from . import rnn
from . import estimator
