"""NDArray: the imperative tensor, TPU-native.

Reference parity: python/mxnet/ndarray/ndarray.py + src/ndarray/ndarray.cc.

Design (SURVEY.md §1): an NDArray wraps an immutable `jax.Array` living in
PJRT-managed memory (HBM on TPU). MXNet's mutable semantics (`x += 1`,
`x[2:5] = 0`, `copyto`) are provided by *rebinding* the wrapper to the new
functional value — an SSA rename, which is exactly what the reference's
engine does logically with its var version counters. Ops dispatch eagerly
through JAX, which queues them asynchronously on the device stream — the same
async-execution model as the reference's ThreadedEngine, with XLA doing the
device-side scheduling. `wait_to_read()` maps to `block_until_ready()`.

Autograd: every op executed under `autograd.record()` is appended to a tape
via `autograd.record_op`; `backward()` replays the tape through `jax.vjp`
(see mxnet_tpu/autograd.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..base import MXNetError, _np_dtype, numeric_types
from ..context import Context, current_context
from ..observability import tracer as _tracer

__all__ = ["NDArray", "zeros", "ones", "full", "empty", "array", "arange",
           "linspace", "eye", "zeros_like", "ones_like", "full_like",
           "from_numpy", "_apply", "_wrap_apply", "waitall"]


def _ctx_of_jax(arr):
    try:
        dev = list(arr.devices())[0]
    except Exception:
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    accels = [d for d in jax.devices() if d.platform != "cpu"]
    try:
        idx = accels.index(dev)
    except ValueError:
        idx = 0
    return Context("tpu", idx)


_np_ndarray_cls = None  # set by mxnet_tpu.numpy on import (mx.np arrays)


def _apply(fn, nd_inputs, kwargs=None, n_out=1):
    """Execute a pure function over NDArray inputs; wrap + record outputs.

    This is the single imperative dispatch point (reference: MXImperativeInvoke).
    np-ness propagates: if any input is an mx.np ndarray, outputs are too —
    this one rule carries the numpy front end through every op, Gluon block
    and the autograd tape without a parallel dispatch path.
    """
    kwargs = kwargs or {}
    raw = [x._data for x in nd_inputs]
    if _tracer.ACTIVE and _tracer.sample_op():
        # SAMPLED op span (1-in-N, MXTPU_TRACE_OP_SAMPLE): per-op tracing
        # at full rate would dominate an imperative trace; the cold branch
        # above is one module-attribute load when tracing is off
        from time import perf_counter_ns
        name = getattr(fn, "__name__", None) or "op"
        if name == "<lambda>":
            name = getattr(fn, "__qualname__", name).split(".<locals>")[0]
        t0 = perf_counter_ns()
        out = fn(*raw, **kwargs)
        t1 = perf_counter_ns()
        _tracer.complete(f"nd.{name.lstrip('_')}", t0, t1, cat="op",
                         args={"sampled": _tracer._op_sample_rate})
        from .. import profiler
        profiler.record_op(f"nd.{name.lstrip('_')}", (t1 - t0) / 1e9)
    else:
        out = fn(*raw, **kwargs)
    if n_out == 1 and not isinstance(out, tuple):
        outs = (out,)
    else:
        outs = tuple(out)
    cls = NDArray
    if _np_ndarray_cls is not None:
        for x in nd_inputs:
            if isinstance(x, _np_ndarray_cls):
                cls = _np_ndarray_cls
                break
    nd_outs = tuple(cls(o) for o in outs)
    if autograd.is_recording():
        autograd.record_op(fn, nd_inputs, kwargs, nd_outs)
    return nd_outs[0] if n_out == 1 and len(nd_outs) == 1 else nd_outs


def _wrap_apply(fn, nd_inputs, kwargs, n_out):
    """Like _apply but always returns a tuple (used by autograd.grad)."""
    out = _apply(fn, nd_inputs, kwargs, n_out=n_out)
    return out if isinstance(out, tuple) else (out,)


def _lift(other, like=None):
    """Coerce a scalar/numpy/NDArray operand to (NDArray | scalar)."""
    if isinstance(other, NDArray):
        return other
    if isinstance(other, numeric_types):
        return other
    if isinstance(other, (np.ndarray, list, tuple)):
        return NDArray(jnp.asarray(other))
    if isinstance(other, jax.Array):
        return NDArray(other)
    raise TypeError(f"cannot operate NDArray with {type(other)}")


def _binary(fn, a, b):
    b = _lift(b)
    if isinstance(b, NDArray):
        return _apply(fn, [a, b])
    return _apply(lambda x, _s=b: fn(x, _s), [a])


def _rbinary(fn, a, b):
    b = _lift(b)
    if isinstance(b, NDArray):
        return _apply(fn, [b, a])
    return _apply(lambda x, _s=b: fn(_s, x), [a])


class NDArray:
    """Multi-dimensional array on a device context (TPU-first).

    Wraps a `jax.Array`. Supports the reference NDArray surface: asynchronous
    imperative ops, in-place arithmetic, slicing assignment, autograd
    integration, and context movement.
    """
    __slots__ = ("_data", "_grad", "_grad_req", "_tape_ref", "__weakref__")
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data, dtype=dtype)
        elif dtype is not None and data.dtype != np.dtype(dtype):
            data = data.astype(dtype)
        if ctx is not None:
            data = jax.device_put(data, Context(ctx).jax_device)
        self._data = data
        self._grad = None
        self._grad_req = "null"
        self._tape_ref = None

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return _ctx_of_jax(self._data)

    ctx = context

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return self.transpose()

    @property
    def stype(self):
        return "default"

    def __repr__(self):
        return f"\n{np.asarray(self._data)}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of an NDArray with multiple "
                             "elements is ambiguous.")
        return bool(np.asarray(self._data))

    def __float__(self):
        return float(np.asarray(self._data))

    def __int__(self):
        return int(np.asarray(self._data))

    def __index__(self):
        return int(np.asarray(self._data))

    def __hash__(self):
        return id(self)

    # ------------------------------------------------------------- transfers
    def asnumpy(self):
        """Copy to a numpy array (blocks until computed — reference parity)."""
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    item = asscalar

    def astype(self, dtype, copy=True):
        dtype = _np_dtype(dtype)
        if not copy and self._data.dtype == dtype:
            return self
        return _apply(lambda a, _d=dtype: a.astype(_d), [self])

    def copy(self):
        # underlying jax.Array is immutable, so sharing the buffer is a
        # semantically correct (and free) copy
        return type(self)(self._data)

    def copyto(self, other):
        """Copy into another NDArray (rebind) or onto a Context."""
        if isinstance(other, NDArray):
            other._assign_value(jax.device_put(
                self._data.astype(other.dtype), other.context.jax_device))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device))
        raise TypeError(f"copyto does not support type {type(other)}")

    def as_in_context(self, ctx):
        ctx = Context(ctx)
        if ctx == self.context:
            return self
        return type(self)(jax.device_put(self._data, ctx.jax_device))

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def to_device(self, ctx):
        return self.as_in_context(ctx)

    def wait_to_read(self):
        """Block until the value is materialised (reference: WaitToRead)."""
        self._data.block_until_ready()
        return self

    def detach(self):
        return type(self)(self._data)

    # ------------------------------------------------------------- autograd
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer so backward() writes into `.grad`."""
        self._grad = type(self)(jnp.zeros_like(self._data))
        self._grad_req = grad_req
        self._tape_ref = None

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ---------------------------------------------------- mutation (rebind)
    def _rebind(self, jax_value):
        """Raw SSA rename: point this wrapper at a new device value."""
        self._data = jax_value
        self._tape_ref = None

    def _assign(self, out_nd):
        """In-place op result: adopt value *and* tape identity of out_nd."""
        self._data = out_nd._data
        self._tape_ref = out_nd._tape_ref
        return self

    def _assign_value(self, jax_value):
        self._data = jax_value
        self._tape_ref = None
        return self

    # ------------------------------------------------------------- indexing
    @staticmethod
    def _unwrap_index(key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(NDArray._unwrap_index(k) for k in key)
        if isinstance(key, slice) or key is None or key is Ellipsis:
            return key
        return key

    def __getitem__(self, key):
        key = NDArray._unwrap_index(key)
        return _apply(lambda a, _k=key: a[_k], [self])

    def __setitem__(self, key, value):
        if isinstance(key, slice) and key == slice(None) and not isinstance(value, NDArray):
            # x[:] = scalar/array — full overwrite
            newv = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype), self.shape)
            self._assign_value(jax.device_put(newv, self.context.jax_device))
            return
        key_u = NDArray._unwrap_index(key)
        if isinstance(value, NDArray):
            out = _apply(lambda a, v, _k=key_u: a.at[_k].set(v.astype(a.dtype)),
                         [self, value])
        else:
            val = jnp.asarray(value)
            out = _apply(lambda a, _k=key_u, _v=val: a.at[_k].set(_v.astype(a.dtype)),
                         [self])
        self._assign(out)

    # ------------------------------------------------------------ arithmetic
    def __add__(self, other):
        return _binary(jnp.add, self, other)
    __radd__ = __add__

    def __sub__(self, other):
        return _binary(jnp.subtract, self, other)

    def __rsub__(self, other):
        return _rbinary(jnp.subtract, self, other)

    def __mul__(self, other):
        return _binary(jnp.multiply, self, other)
    __rmul__ = __mul__

    def __truediv__(self, other):
        return _binary(jnp.divide, self, other)

    def __rtruediv__(self, other):
        return _rbinary(jnp.divide, self, other)

    def __floordiv__(self, other):
        return _binary(jnp.floor_divide, self, other)

    def __mod__(self, other):
        return _binary(jnp.mod, self, other)

    def __rmod__(self, other):
        return _rbinary(jnp.mod, self, other)

    def __pow__(self, other):
        return _binary(jnp.power, self, other)

    def __rpow__(self, other):
        return _rbinary(jnp.power, self, other)

    def __matmul__(self, other):
        return _binary(jnp.matmul, self, other)

    def __neg__(self):
        return _apply(jnp.negative, [self])

    def __abs__(self):
        return _apply(jnp.abs, [self])

    def __iadd__(self, other):
        return self._assign(self + other)

    def __isub__(self, other):
        return self._assign(self - other)

    def __imul__(self, other):
        return self._assign(self * other)

    def __itruediv__(self, other):
        return self._assign(self / other)

    # ------------------------------------------------------------ comparison
    def __eq__(self, other):
        if other is None:
            return False
        return _binary(lambda a, b: (a == b).astype(jnp.float32), self, other)

    def __ne__(self, other):
        if other is None:
            return True
        return _binary(lambda a, b: (a != b).astype(jnp.float32), self, other)

    def __lt__(self, other):
        return _binary(lambda a, b: (a < b).astype(jnp.float32), self, other)

    def __le__(self, other):
        return _binary(lambda a, b: (a <= b).astype(jnp.float32), self, other)

    def __gt__(self, other):
        return _binary(lambda a, b: (a > b).astype(jnp.float32), self, other)

    def __ge__(self, other):
        return _binary(lambda a, b: (a >= b).astype(jnp.float32), self, other)

    # ------------------------------------------------------------ shape ops
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        shape = tuple(int(s) for s in shape)
        # reference reshape magic values: 0 = copy dim, -1 = infer
        out_shape = []
        for i, s in enumerate(shape):
            if s == 0:
                out_shape.append(self.shape[i])
            else:
                out_shape.append(s)
        return _apply(lambda a, _s=tuple(out_shape): a.reshape(_s), [self])

    def reshape_like(self, other):
        return _apply(lambda a, b: a.reshape(b.shape), [self, _lift(other)])

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        axes = axes if axes else None
        return _apply(lambda a, _ax=axes: jnp.transpose(a, _ax), [self])

    def flatten(self):
        """Reference semantics: collapse all trailing dims -> (batch, -1)."""
        return _apply(lambda a: a.reshape(a.shape[0], -1) if a.ndim > 1 else a, [self])

    def expand_dims(self, axis):
        return _apply(lambda a, _ax=axis: jnp.expand_dims(a, _ax), [self])

    def squeeze(self, axis=None):
        return _apply(lambda a, _ax=axis: jnp.squeeze(a, _ax), [self])

    def broadcast_to(self, shape):
        return _apply(lambda a, _s=tuple(shape): jnp.broadcast_to(a, _s), [self])

    def broadcast_like(self, other):
        return _apply(lambda a, b: jnp.broadcast_to(a, b.shape), [self, _lift(other)])

    def tile(self, reps):
        return _apply(lambda a, _r=tuple(reps) if not isinstance(reps, int) else reps:
                      jnp.tile(a, _r), [self])

    def repeat(self, repeats, axis=None):
        return _apply(lambda a, _r=repeats, _ax=axis: jnp.repeat(a, _r, _ax), [self])

    def swapaxes(self, a1, a2):
        return _apply(lambda a, _a=a1, _b=a2: jnp.swapaxes(a, _a, _b), [self])

    def split(self, num_outputs, axis=0):
        return _apply(lambda a, _n=num_outputs, _ax=axis:
                      tuple(jnp.split(a, _n, _ax)), [self], n_out=num_outputs)

    def slice_axis(self, axis, begin, end):
        return _apply(lambda a, _ax=axis, _b=begin, _e=end:
                      jax.lax.slice_in_dim(a, _b, _e if _e is not None else a.shape[_ax],
                                           axis=_ax), [self])

    # ------------------------------------------------------------ reductions
    def _reduce(self, fn, axis=None, keepdims=False):
        if isinstance(axis, list):
            axis = tuple(axis)
        return _apply(lambda a, _ax=axis, _k=keepdims: fn(a, axis=_ax, keepdims=_k),
                      [self])

    def sum(self, axis=None, keepdims=False):
        return self._reduce(jnp.sum, axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce(jnp.mean, axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce(jnp.max, axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce(jnp.min, axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce(jnp.prod, axis, keepdims)

    def argmax(self, axis=None, keepdims=False):
        return _apply(lambda a, _ax=axis, _k=keepdims:
                      jnp.argmax(a, axis=_ax, keepdims=_k).astype(jnp.float32), [self])

    def argmin(self, axis=None, keepdims=False):
        return _apply(lambda a, _ax=axis, _k=keepdims:
                      jnp.argmin(a, axis=_ax, keepdims=_k).astype(jnp.float32), [self])

    def norm(self, ord=2, axis=None, keepdims=False):
        return _apply(lambda a, _o=ord, _ax=axis, _k=keepdims:
                      jnp.linalg.norm(a.reshape(-1) if _ax is None else a,
                                      ord=_o, axis=_ax, keepdims=_k), [self])

    # -------------------------------------------------------------- math ops
    def _unary(self, fn):
        return _apply(fn, [self])

    def abs(self):
        return self._unary(jnp.abs)

    def exp(self):
        return self._unary(jnp.exp)

    def log(self):
        return self._unary(jnp.log)

    def sqrt(self):
        return self._unary(jnp.sqrt)

    def square(self):
        return self._unary(jnp.square)

    def sign(self):
        return self._unary(jnp.sign)

    def round(self):
        return self._unary(jnp.round)

    def floor(self):
        return self._unary(jnp.floor)

    def ceil(self):
        return self._unary(jnp.ceil)

    def sigmoid(self):
        return self._unary(jax.nn.sigmoid)

    def tanh(self):
        return self._unary(jnp.tanh)

    def relu(self):
        return self._unary(jax.nn.relu)

    def softmax(self, axis=-1):
        return _apply(lambda a, _ax=axis: jax.nn.softmax(a, axis=_ax), [self])

    def log_softmax(self, axis=-1):
        return _apply(lambda a, _ax=axis: jax.nn.log_softmax(a, axis=_ax), [self])

    def clip(self, a_min=None, a_max=None):
        return _apply(lambda a, _lo=a_min, _hi=a_max: jnp.clip(a, _lo, _hi), [self])

    def dot(self, other):
        return _binary(jnp.dot, self, _lift(other))

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return _apply(lambda a, _d=depth, _on=on_value, _off=off_value:
                      jax.nn.one_hot(a.astype(jnp.int32), _d) * (_on - _off) + _off,
                      [self])

    def topk(self, k=1, axis=-1, ret_typ="indices", is_ascend=False):
        def _topk(a, _k=k, _ax=axis, _ret=ret_typ, _asc=is_ascend):
            x = -a if _asc else a
            x = jnp.moveaxis(x, _ax, -1)
            vals, idxs = jax.lax.top_k(x, _k)
            if _asc:
                vals = -vals
            vals = jnp.moveaxis(vals, -1, _ax)
            idxs = jnp.moveaxis(idxs, -1, _ax).astype(jnp.float32)
            if _ret == "value":
                return vals
            if _ret == "both":
                return (vals, idxs)
            return idxs
        n_out = 2 if ret_typ == "both" else 1
        return _apply(_topk, [self], n_out=n_out)

    def sort(self, axis=-1, is_ascend=True):
        return _apply(lambda a, _ax=axis, _asc=is_ascend:
                      jnp.sort(a, axis=_ax) if _asc else -jnp.sort(-a, axis=_ax),
                      [self])

    def argsort(self, axis=-1, is_ascend=True):
        return _apply(lambda a, _ax=axis, _asc=is_ascend:
                      (jnp.argsort(a, axis=_ax) if _asc
                       else jnp.argsort(-a, axis=_ax)).astype(jnp.float32), [self])

    def take(self, indices, axis=0):
        idx = _lift(indices)
        return _apply(lambda a, i, _ax=axis: jnp.take(a, i.astype(jnp.int32), axis=_ax),
                      [self, idx])

    def pick(self, index, axis=-1, keepdims=False):
        idx = _lift(index)
        return _apply(lambda a, i, _ax=axis, _k=keepdims:
                      jnp.take_along_axis(a, jnp.expand_dims(i.astype(jnp.int32), _ax),
                                          axis=_ax)
                      if _k else
                      jnp.squeeze(jnp.take_along_axis(
                          a, jnp.expand_dims(i.astype(jnp.int32), _ax), axis=_ax), _ax),
                      [self, idx])

    def tostype(self, stype):
        if stype != "default":
            raise MXNetError("sparse storage is not supported on TPU "
                             "(SURVEY.md §2 #49)")
        return self


# ---------------------------------------------------------------------------
# creation ops (reference: mx.nd.zeros/ones/...)
# ---------------------------------------------------------------------------
def _place(val, ctx):
    ctx = Context(ctx) if ctx is not None else current_context()
    return NDArray(jax.device_put(val, ctx.jax_device))


def zeros(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _place(jnp.zeros(shape, dtype=_np_dtype(dtype)), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _place(jnp.ones(shape, dtype=_np_dtype(dtype)), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return _place(jnp.full(shape, val, dtype=_np_dtype(dtype)), ctx)


def empty(shape, ctx=None, dtype=None):
    """Allocate without defined contents. Documented divergence: XLA has no
    uninitialised-buffer primitive (every jnp array is a defined value), so
    this returns zeros — same shape/dtype/placement contract, deterministic
    contents. Reference: ndarray.empty leaves memory uninitialised."""
    return zeros(shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        source_array = source_array._data
    if dtype is None:
        arr = np.asarray(source_array)
        dtype = arr.dtype if arr.dtype != np.float64 else np.float32
        source_array = arr
    return _place(jnp.asarray(source_array, dtype=_np_dtype(dtype)), ctx)


def from_numpy(arr, zero_copy=False):
    return array(arr)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    out = jnp.arange(start, stop, step, dtype=_np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return _place(out, ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    return _place(jnp.linspace(start, stop, num, endpoint=endpoint,
                               dtype=_np_dtype(dtype)), ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    return _place(jnp.eye(N, M if M else None, k, dtype=_np_dtype(dtype)), ctx)


def zeros_like(other, **kwargs):
    return _apply(jnp.zeros_like, [other])


def ones_like(other, **kwargs):
    return _apply(jnp.ones_like, [other])


def full_like(other, fill_value, **kwargs):
    return _apply(lambda a, _v=fill_value: jnp.full_like(a, _v), [other])


def waitall():
    """Block until all queued computation is materialised
    (reference: MXNDArrayWaitAll)."""
    try:
        jax.effects_barrier()
    except Exception:
        pass
