"""Draft proposers for speculative multi-token decoding (ISSUE 12).

The serving fast path drafts k tokens per turn and verifies them with
ONE batched pass through the widened decode executable
(`DecodeRuntime.decode_multi`); whatever the proposer gets wrong only
costs acceptance rate, never correctness — the committed tokens are
always the target model's own greedy choices. That freedom is why the
default proposer needs no draft model at all: n-gram / prompt-lookup
decoding (the self-speculation family) just searches the request's OWN
committed token history for the most recent earlier occurrence of its
current suffix and proposes the continuation that followed it. On the
prefix-heavy traffic the cache targets (templates, repetitive
structures, model output loops) that continuation is right often enough
to collapse several decode turns into one.

Host-side and allocation-free per turn: `known` is the request's
committed sequence (``[BOS] + prompt + generated``), a plain int list
that is at most `max_prompt_len + max_new_tokens` long.
"""
from __future__ import annotations

__all__ = ["propose_ngram"]


def propose_ngram(known, k, ngram=2):
    """Propose up to `k` draft tokens continuing `known` by prompt
    lookup: find the MOST RECENT earlier occurrence of the trailing
    `ngram` tokens (falling back to shorter suffixes, down to 1) and
    return the tokens that followed it. Returns [] when the history has
    no repeated suffix — the caller then runs the turn unspeculated."""
    n = len(known)
    k = int(k)
    if k <= 0 or n < 2:
        return []
    for g in range(min(int(ngram), n - 1), 0, -1):
        pat = known[n - g:]
        # latest j < n - g with known[j:j+g] == pat (the match may
        # overlap the suffix itself — periodic loops resolve correctly)
        for j in range(n - g - 1, -1, -1):
            if known[j:j + g] == pat:
                cont = known[j + g:j + g + k]
                if cont:
                    return [int(t) for t in cont]
                break   # suffix matched at j but nothing follows; a
                        # shorter suffix may still find a continuation
    return []
