"""Shared candidate-sweep protocol for the benchmark workers.

One implementation of the budget-gated, failure-tolerant sweep both
bench.py (ResNet batch sizes) and bench_bert.py (BERT batch sizes) run:
- candidates after the first only START inside `budget_s` (a slow
  compile can't eat the supervisor's per-attempt timeout);
- a failing candidate (e.g. OOM at the larger batch) is skipped, never
  fatal, as long as at least one candidate lands;
- `on_best(value)` fires whenever the best-so-far improves, letting the
  caller checkpoint its JSON line (the supervisor keeps the LAST
  parseable stdout line, so a wedged later candidate can't lose a
  completed measurement).
"""
from __future__ import annotations

import sys
import time


def timed_measure(step, params, mom, data, steps, items_per_dispatch,
                  tag="bench"):
    """The shared measurement protocol: 2 warmup dispatches (compile +
    stabilise), host-fetch sync (block_until_ready doesn't block under
    the axon tunnel), then `steps` timed dispatches. Returns
    items_per_dispatch * steps / elapsed."""
    params, mom, loss = step(params, mom, *data)
    params, mom, loss = step(params, mom, *data)
    float(loss)
    t0 = time.monotonic()
    for _ in range(steps):
        params, mom, loss = step(params, mom, *data)
    final_loss = float(loss)
    dt = time.monotonic() - t0
    rate = items_per_dispatch * steps / dt
    print(f"[{tag}] loss={final_loss:.4f} dt={dt:.3f}s "
          f"-> {rate:.1f} items/s", file=sys.stderr)
    return rate


def make_sgd_step(loss_fn, aux_idx, lr, mu, unroll=1):
    """The jitted SGD-momentum train step every bench worker uses:
    value_and_grad(loss_fn) -> per-tensor momentum update -> aux (BN
    running stats) spliced back into the param list, optionally unrolled
    k steps per dispatch (the BENCH_UNROLL lever). Donation caveat lives
    with the callers: donate COPIES of params, the originals die."""
    unroll = max(1, int(unroll))  # 0/negative would zero the numerator
    import jax

    def step_1(p, mom, *data):
        (loss, aux), g = jax.value_and_grad(loss_fn, has_aux=True)(p, *data)
        new_mom = [mu * m + gg.astype(m.dtype) for m, gg in zip(mom, g)]
        new_p = [pp - lr * m for pp, m in zip(p, new_mom)]
        for i, v in zip(aux_idx, aux):
            new_p[i] = v
        return new_p, new_mom, loss

    def step_k(p, mom, *data):
        loss = None
        for _ in range(unroll):
            p, mom, loss = step_1(p, mom, *data)
        return p, mom, loss

    return jax.jit(step_k if unroll > 1 else step_1,
                   donate_argnums=(0, 1))


def sweep(candidates, budget_s, run_one, on_best=None, tag="bench"):
    """Run `run_one(candidate) -> float` over candidates; return
    (best_value, best_candidate). Raises RuntimeError if none land."""
    best, best_cand = 0.0, None
    t_start = time.monotonic()
    for i, cand in enumerate(candidates):
        if i > 0 and time.monotonic() - t_start > budget_s:
            print(f"[{tag}] sweep budget spent; skipping {cand}",
                  file=sys.stderr)
            continue
        try:
            value = run_one(cand)
        except Exception as e:  # e.g. OOM at the larger candidate
            print(f"[{tag}] candidate {cand} failed: {e!r}",
                  file=sys.stderr)
            continue
        if value > best:
            best, best_cand = value, cand
            if on_best is not None:
                on_best(best)
    if best_cand is None:
        raise RuntimeError(f"[{tag}] no sweep candidate completed")
    return best, best_cand


class BackgroundEngineLoad:
    """Sustained background dependency-engine flood (ISSUE 7): a producer
    thread keeps `target` short sleep tasks live in one cancellable
    TaskGroup at PRIORITY_BACKGROUND — the stand-in for a co-tenant
    training loop's host-side work (prefetch staging, async checkpoint
    IO). One implementation shared by `bench_serve.py
    --background-train` and the `tools/check_qos.py` tier-1 gate so the
    bench and the gate measure the same contention."""

    def __init__(self, target, task_s=0.02):
        import threading
        from mxnet_tpu import engine
        self._engine = engine
        self.group = engine.TaskGroup("background_load")
        self.target = int(target)
        self.task_s = float(task_s)
        self._stop = threading.Event()
        self.error = None     # a dead flood thread makes any "no
                              # starvation under load" assertion vacuous:
                              # consumers must check this after the run
        self._thread = threading.Thread(target=self._produce, daemon=True)

    def _produce(self):
        while not self._stop.is_set():
            short = self.target - self.group.live()
            try:
                for _ in range(max(0, short)):
                    self.group.push(
                        lambda: time.sleep(self.task_s),
                        priority=self._engine.PRIORITY_BACKGROUND)
            except self._engine.EngineQueueFull:
                pass          # bounded background class: back off, keep
                              # flooding — the load stays sustained
            except BaseException as exc:  # noqa: BLE001
                self.error = exc
                return
            time.sleep(0.005)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)
        self.group.cancel()
        self.group.drain(timeout=60)
        if self.error is not None and not any(exc):
            # surface a dead producer thread: a run "under load" whose
            # flood silently stopped would pass its contention
            # assertions vacuously
            raise RuntimeError(
                f"background flood thread died: {self.error!r}")
        return False
