"""Linear-algebra ops (reference: src/operator/tensor/la_op.cc — mx.nd.linalg)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ndarray.ndarray import _apply, _lift

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "sumlogdiag",
           "syrk", "gelqf", "syevd", "inverse", "det", "slogdet", "cholesky",
           "qr", "svd", "solve", "norm", "extractdiag", "makediag",
           "extracttrian", "maketrian"]


def gemm(A, B, C, alpha=1.0, beta=1.0, transpose_a=False, transpose_b=False):
    def fn(a, b, c, _al=alpha, _be=beta, _ta=transpose_a, _tb=transpose_b):
        if _ta:
            a = jnp.swapaxes(a, -1, -2)
        if _tb:
            b = jnp.swapaxes(b, -1, -2)
        return _al * jnp.matmul(a, b) + _be * c
    return _apply(fn, [A, _lift(B), _lift(C)])


def gemm2(A, B, alpha=1.0, transpose_a=False, transpose_b=False):
    def fn(a, b, _al=alpha, _ta=transpose_a, _tb=transpose_b):
        if _ta:
            a = jnp.swapaxes(a, -1, -2)
        if _tb:
            b = jnp.swapaxes(b, -1, -2)
        return _al * jnp.matmul(a, b)
    return _apply(fn, [A, _lift(B)])


def potrf(A):
    """Cholesky factor (lower)."""
    return _apply(jnp.linalg.cholesky, [A])


cholesky = potrf


def potri(A):
    """Inverse from Cholesky factor: (A A^T)^-1 given lower A."""
    def fn(a):
        eye = jnp.eye(a.shape[-1], dtype=a.dtype)
        linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
        return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)
    return _apply(fn, [A])


def trsm(A, B, alpha=1.0, rightside=False, lower=True, transpose=False):
    def fn(a, b, _al=alpha, _r=rightside, _lo=lower, _t=transpose):
        if _r:
            # X A = alpha B  ->  A^T X^T = alpha B^T
            xt = jax.scipy.linalg.solve_triangular(
                jnp.swapaxes(a, -1, -2), jnp.swapaxes(_al * b, -1, -2),
                lower=not _lo if not _t else _lo)
            return jnp.swapaxes(xt, -1, -2)
        return jax.scipy.linalg.solve_triangular(a, _al * b, lower=_lo, trans=int(_t))
    return _apply(fn, [A, _lift(B)])


def trmm(A, B, alpha=1.0, rightside=False, lower=True, transpose=False):
    def fn(a, b, _al=alpha, _r=rightside, _lo=lower, _t=transpose):
        tri = jnp.tril(a) if _lo else jnp.triu(a)
        if _t:
            tri = jnp.swapaxes(tri, -1, -2)
        return _al * (jnp.matmul(b, tri) if _r else jnp.matmul(tri, b))
    return _apply(fn, [A, _lift(B)])


def sumlogdiag(A):
    return _apply(lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                                    axis=-1), [A])


def syrk(A, alpha=1.0, transpose=False):
    def fn(a, _al=alpha, _t=transpose):
        at = jnp.swapaxes(a, -1, -2)
        return _al * (jnp.matmul(at, a) if _t else jnp.matmul(a, at))
    return _apply(fn, [A])


def gelqf(A):
    """LQ factorisation (reference: linalg_gelqf)."""
    def fn(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    return _apply(fn, [A], n_out=2)


def syevd(A):
    def fn(a):
        w, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), w
    return _apply(fn, [A], n_out=2)


def inverse(A):
    return _apply(jnp.linalg.inv, [A])


def det(A):
    return _apply(jnp.linalg.det, [A])


def slogdet(A):
    return _apply(lambda a: tuple(jnp.linalg.slogdet(a)), [A], n_out=2)


def qr(A):
    return _apply(lambda a: tuple(jnp.linalg.qr(a)), [A], n_out=2)


def svd(A):
    return _apply(lambda a: tuple(jnp.linalg.svd(a, full_matrices=False)), [A],
                  n_out=3)


def solve(A, B):
    return _apply(jnp.linalg.solve, [A, _lift(B)])


def norm(A, ord=2, axis=None, keepdims=False):
    return A.norm(ord=ord, axis=axis, keepdims=keepdims)


# -- diagonal / triangle packing (reference: la_op.cc extractdiag /
# makediag / extracttrian / maketrian) -------------------------------------
def _trian_indices(n, offset, lower):
    import numpy as onp
    return (onp.tril_indices(n, k=offset) if lower
            else onp.triu_indices(n, k=offset))


def _trian_count(n, offset, lower):
    """#entries in the (lower: tril, upper: triu) triangle at `offset`
    of an n x n matrix — arithmetic, no index materialisation."""
    k = offset if lower else -offset
    # tril(n, k): sum_i clip(i + k + 1, 0, n)
    if k >= n - 1:
        return n * n
    if k < -n:
        return 0
    full_rows = max(0, -(k + 1))          # rows contributing 0
    m = n - full_rows                     # rows with i + k + 1 in [1, n]
    start = full_rows + k + 1             # count at first contributing row
    capped = max(0, m - (n - start))      # rows already capped at n
    ramp = m - capped
    return start * ramp + ramp * (ramp - 1) // 2 + capped * n


def _trian_n_for(length, offset, lower):
    lo, hi = 1, 1 << 20
    while lo < hi:
        mid = (lo + hi) // 2
        if _trian_count(mid, offset, lower) < length:
            lo = mid + 1
        else:
            hi = mid
    if _trian_count(lo, offset, lower) != length:
        raise ValueError(f"maketrian: no matrix size yields a packed "
                         f"length of {length} at offset {offset}")
    return lo


def extractdiag_k(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


def makediag_k(v, offset=0):
    n = v.shape[-1] + abs(int(offset))
    idx = jnp.arange(v.shape[-1])
    r = idx + max(0, -offset)
    c = idx + max(0, offset)
    out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
    return out.at[..., r, c].set(v)


def extracttrian_k(a, offset=0, lower=True):
    rows, cols = _trian_indices(a.shape[-1], int(offset), bool(lower))
    return a[..., rows, cols]


def maketrian_k(v, offset=0, lower=True):
    n = _trian_n_for(v.shape[-1], int(offset), bool(lower))
    rows, cols = _trian_indices(n, int(offset), bool(lower))
    out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
    return out.at[..., rows, cols].set(v)


def extractdiag(A, offset=0):
    return _apply(lambda a: extractdiag_k(a, int(offset)), [A])


def makediag(A, offset=0):
    return _apply(lambda a: makediag_k(a, int(offset)), [A])


def extracttrian(A, offset=0, lower=True):
    return _apply(lambda a: extracttrian_k(a, offset, lower), [A])


def maketrian(A, offset=0, lower=True):
    return _apply(lambda a: maketrian_k(a, offset, lower), [A])
