"""Dependency-engine drive for the serving scheduler (ISSUE 6, QoS'd in
ISSUE 7).

The serving crank is host-side async work — exactly what the dependency
engine (mxnet_tpu/engine.py) schedules for prefetch and checkpoint IO —
so the decode loop runs as engine tasks rather than a dedicated thread:

  * ONE loop task at a time, serialised on a private engine `Var` (the
    same write-var discipline as the prefetcher's staging slots, so the
    race detector covers the serving loop too);
  * `kick()` arms the loop when work arrives and is a no-op while a loop
    task is already scheduled — submits never pile up tasks;
  * the task cranks `scheduler.step()` until the engine is idle
    (bounded per-task burst, then re-pushes itself, so checkpoint saves
    and prefetch staging interleave with decoding instead of starving
    behind an unbounded serving task).

QoS (ISSUE 7): loop tasks are PRIORITY_HIGH members of a `TaskGroup` —
they preempt queued background staging/checkpoint work at dispatch time
(decode p99 stays bounded under a background flood; aging keeps the
background work from starving outright), and `close()` cancels any
queued loop task through the group instead of waiting it out.

Fault discipline: a loop-task failure (e.g. an injected `engine.task`
fault) surfaces through the engine's sticky failure report
(`engine.failures()`) like every other engine task — AND the loop
re-arms itself on a FRESH var (the native engine poisons a failed
task's vars permanently) so serving survives the fault instead of
silently wedging every later kick. Restarts count into
`serve_loop_restarts`.
"""
from __future__ import annotations

import threading
import time

from .. import engine
from ..observability import registry as _obs_registry

__all__ = ["EngineLoop"]

# steps one engine task cranks before re-pushing itself: long enough to
# amortise the push, short enough that other engine users interleave
_BURST = 64


class EngineLoop:
    def __init__(self, scheduler):
        self._sched = scheduler
        self._var = engine.Var()
        self._lock = threading.Lock()
        self._armed = False
        self._closed = False
        self._group = engine.TaskGroup("serve.loop")
        self.restarts = 0
        self._consec_failures = 0
        self._m_restarts = _obs_registry().counter("serve_loop_restarts")

    def kick(self):
        """Ensure a loop task is scheduled (no-op when one already is)."""
        with self._lock:
            if self._armed or self._closed:
                return
            self._armed = True
        self._push()

    def _retry_push_later(self, delay):
        """Re-attempt _push off-worker after `delay` (one timer at a
        time: _armed stays set, so kick() no-ops while it is pending)."""
        timer = threading.Timer(delay, self._push)
        timer.daemon = True
        timer.start()

    def _push(self):
        with self._lock:
            if self._closed:           # a backoff timer may outlive close
                self._armed = False
                return
            var = self._var
        try:
            fut = engine.push(self._loop_task, write_vars=[var],
                              priority=engine.PRIORITY_HIGH,
                              group=self._group)
        except engine.EngineQueueFull:
            # a bounded HIGH-class queue rejected the loop task: stay
            # armed and retry off-worker shortly — clients parked in
            # Request.result(timeout) never call kick(), so disarming
            # here would strand mid-decode requests until some external
            # submit happened to land.
            self._retry_push_later(0.05)
            return
        except BaseException:   # noqa: BLE001
            # any OTHER push failure (engine closed under a shutdown
            # race, inner-engine error): swallowing it in a Timer/done-
            # callback thread would leave _armed stuck True with no loop
            # task — kick() no-ops forever and every queued request
            # strands. Stay armed and retry with bounded exponential
            # backoff instead; serve_loop_restarts makes it visible
            # (restarts counts it too, so counter and attribute agree).
            with self._lock:
                if self._closed:
                    self._armed = False
                    return
                self.restarts += 1
                self._consec_failures += 1
                streak = self._consec_failures
            self._m_restarts.inc()
            self._retry_push_later(min(2.0, 0.05 * (2 ** min(streak, 6))))
            return
        fut.add_done_callback(self._task_done)

    def _task_done(self, fut):
        try:
            exc = fut.exception()
            res = fut.result() if exc is None else None
        except BaseException:          # externally cancelled future
            with self._lock:
                if self._closed:       # close() cancels the group: done
                    self._armed = False
                    return
            # cancelled OUTSIDE close (a stray Future.cancel): armed
            # with no loop task would wedge serving forever — re-push,
            # exactly like a shed loop task
            self._push()
            return
        if exc is None and not engine.skipped(res):
            with self._lock:
                self._consec_failures = 0
            return
        with self._lock:
            if self._closed:
                self._armed = False
                return
            if exc is not None:
                # the loop task itself died (injected engine.task fault,
                # scheduler bug): its var is poisoned on the native
                # engine — re-arm on a FRESH var and keep cranking; the
                # error stays visible in engine.failures()
                self._var = engine.Var()
                self.restarts += 1
                self._consec_failures += 1
            streak = self._consec_failures
        if exc is not None:
            self._m_restarts.inc()
        # exc None + skipped(res): the queued loop task was SHED by a
        # bounded high-class queue (close() cancels set _closed first,
        # handled above) — re-push so serving resumes when the queue
        # drains rather than wedging armed-but-taskless
        if streak > 1:
            # a PERSISTENTLY failing loop (deterministic scheduler bug,
            # prob=1.0 fault left armed) must not hot-spin a worker:
            # re-arm off-worker with bounded exponential backoff
            self._retry_push_later(
                min(0.05 * (2 ** min(streak - 2, 5)), 2.0))
            return
        self._push()

    def _loop_task(self):
        for _ in range(_BURST):
            if self._closed:
                break
            if not self._sched.step():
                # no progress: either drained, or queued work is waiting
                # on pages that only in-flight decodes can free — the
                # truthiness of step() guarantees actives keep making
                # progress, so "no progress + pending" means drained-race
                with self._lock:
                    if self._closed or not self._sched.pending_work():
                        self._armed = False
                        return
                continue
        # burst spent (or closing): yield the worker, keep the loop armed
        with self._lock:
            if self._closed or not self._sched.pending_work():
                self._armed = False
                return
        self._push()

    def wait_idle(self, timeout=None):
        """Block until the scheduler drains (engine-task completion plus a
        pending-work poll, since a new submit can re-arm the loop)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                var = self._var
            try:
                engine.wait_for_var(var)
            except (KeyboardInterrupt, SystemExit):
                raise   # an operator's Ctrl-C must break a wedged drain
            except BaseException:   # noqa: BLE001 — the engines store and
                pass    # re-raise BaseExceptions too; a failed loop task
                        # re-arms on a fresh var either way (parity with
                        # _task_done's own except BaseException)
            if not self._sched.pending_work():
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            self.kick()
            time.sleep(0.001)

    def close(self):
        """Stop the loop: cancel any queued-not-started loop task through
        the task group (its future resolves to engine.CANCELLED) and
        drain the in-flight one — close never blocks behind a poisoned
        var."""
        with self._lock:
            self._closed = True
        self._group.cancel()
        self._group.drain()
