"""Shared symbolic multi-head attention decomposition.

The flash-attention blocks (models.bert.MultiHeadSelfAttention,
models.transformer.SelfAttention/CrossAttention) trace eagerly through
the Pallas kernel; for export/serialization they decompose into named
graph ops instead. ONE decomposition lives here so the export numerics
(head reshape, 1/sqrt(head_dim) scale, -1e9 masked softmax) cannot
diverge between models.
"""
from __future__ import annotations

import math


def sym_attention(F, q, k, v, num_heads, units, length=None, causal=False):
    """(B, S, D) projected q/k/v Symbols -> (B, S, D) attention output.

    `length` is an optional (B,) kv valid-length Symbol; `causal` masks
    past-the-row positions — both ride the softmax op's masked form, the
    same kernel the ONNX decomposition pins."""
    h = num_heads

    def heads(t):  # (B, S, D) -> (B, h, S, dh)
        return F.transpose(F.reshape(t, (0, 0, h, -1)), (0, 2, 1, 3))

    kt = F.transpose(F.reshape(k, (0, 0, h, -1)), (0, 2, 3, 1))
    scores = F.batch_dot(heads(q), kt) * (1.0 / math.sqrt(units // h))
    attnw = F.softmax(scores, length=length, axis=-1, causal=causal)
    out = F.batch_dot(attnw, heads(v))
    return F.reshape(F.transpose(out, (0, 2, 1, 3)), (0, 0, -1))
