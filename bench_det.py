"""SSD-512 (ResNet-50 backbone) training throughput, images/sec/chip
(BASELINE.json config 5: "SSD-512 + Faster-RCNN object detection").

One jitted bf16 NHWC train step: SSD-512-resnet50 forward, MultiBox
target matching against the static anchor grid (precomputed once — the
anchors are model constants, matching GluonCV's generate-once design),
softmax classification + Huber localisation loss, SGD-momentum, donated
buffers.

Baseline denominator (BASELINE_IMG_S = 420), defended two ways
(VERDICT r4 item 2):

1. FLOP scaling of the SURVEY §6 ResNet-50 anchor (2500 img/s at
   ~12.3 GFLOP/img-train): SSD-512's backbone runs at 512^2 = 5.2x the
   224^2 pixel count (~21 GFLOP fwd) plus extras and 3x3 heads
   (~3.5 GFLOP), so one train step is ~73 GFLOP/img; a pipeline that
   KEPT ResNet-class MXU efficiency would sustain 2500 * 12.3/73 ~= 420
   images/sec/chip. This is an upper bound on the reference: it assumes
   zero efficiency loss from the multi-scale heads, target matching,
   and the uneven feature-map shapes.
2. Published-ratio check: GluonCV's training speed tables put
   classification ResNet-50 and SSD-512-resnet50 on the same 8xV100
   hardware at a per-GPU throughput ratio of roughly 6-6.5:1 (their
   SSD-512 logs train at ~1/6.3 the img/s of their ResNet-50 runs).
   Applying that empirical pipeline-efficiency ratio to the 2500
   anchor gives 2500/6.3 ~= 395 img/s A100-class.

We keep the HIGHER (more conservative, harder-to-beat) 420 as the
vs_baseline denominator; the ratio-derived ~395 brackets it from
below, so a measured >=1.0x here clears the reference under either
derivation.

Off by default in bench.py's driver line; enable with BENCH_DET=1
(VERDICT r3 item 7). Standalone: `python bench_det.py` prints ONE JSON
line.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_S = 420.0


def build_step(batch, input_size=512):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import extract_pure_fn
    from mxnet_tpu.models.ssd import SSD
    from mxnet_tpu.ops import detection_ops as D

    backbone = 50 if input_size >= 256 else 18
    net = SSD(num_classes=20, backbone_layers=backbone,
              input_size=input_size)
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")

    x = mx.nd.random.uniform(shape=(batch, input_size, input_size, 3),
                             dtype="bfloat16")
    net(x)  # materialise params
    fwd, params = extract_pure_fn(net, x, training=True)
    aux_idx = list(fwd.aux_indices)

    # fixed synthetic scene: 8 boxes/img; targets precomputed OUTSIDE the
    # step (anchor matching depends on labels, not weights — doing it per
    # step would bench the target generator, not the network)
    rng = np.random.RandomState(0)
    M = 8
    wh = rng.uniform(0.1, 0.4, (batch, M, 2))
    xy = rng.uniform(0.0, 0.6, (batch, M, 2))
    # classes in [0, num_classes): multibox_target emits cls+1 (0=bg), so
    # a 1-based label here would index one past the (C+1)-wide logits —
    # an OOB gather that is garbage (NaN loss) on TPU, silently clamped
    # on CPU (found by the first on-chip run of this bench)
    cls = rng.randint(0, 20, (batch, M, 1))
    labels = jnp.asarray(np.concatenate(
        [cls, xy, xy + wh], axis=-1), jnp.float32)
    anchors = jnp.asarray(net.anchors)
    cls_t, loc_t, loc_m = D.multibox_target(anchors, labels, 0.5)
    # OOB class targets are garbage on TPU but CLAMPED on CPU — assert
    # here so a smoke run catches what only the chip would reveal
    assert int(cls_t.max()) <= net.num_classes, int(cls_t.max())

    def loss_fn(p, xb, ct, lt, lm):
        (cls_p, loc_p), aux = fwd(p, xb)
        cls_p = cls_p.astype(jnp.float32)
        loc_p = loc_p.astype(jnp.float32).reshape(ct.shape[0], -1, 4)
        lp = jax.nn.log_softmax(cls_p, axis=-1)
        l_cls = -jnp.mean(jnp.take_along_axis(
            lp, ct.astype(jnp.int32)[..., None], -1))
        d = (loc_p - lt) * lm
        l_loc = jnp.mean(jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                                   jnp.abs(d) - 0.5))
        return l_cls + l_loc, aux

    from bench_util import make_sgd_step
    unroll = max(1, int(os.environ.get("BENCH_DET_UNROLL", "1")))
    step = make_sgd_step(loss_fn, aux_idx, lr=0.01, mu=0.9, unroll=unroll)
    mom = [jnp.zeros_like(p) for p in params]
    data = (x._data, cls_t, loc_t, loc_m)
    return step, params, mom, data, unroll


BASELINE_RCNN_IMG_S = 270.0


def build_rcnn_step(batch, input_size=512, return_parts=False,
                    unroll=1):
    """Full two-stage train step in ONE jitted program: backbone+RPN,
    proposal generation (static-k top-k + NMS), target sampling, RoIAlign
    head, RPN + RCNN losses. The reference runs this as a Python training
    loop around imperative ops; here the whole pipeline compiles into a
    single XLA executable (proposals/NMS are static-shape, so nothing
    falls back to the host between stages). With return_parts=True also
    returns (net, fwd) so callers (tools/det_convergence.py) can run
    held-out eval with the trained params."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.block import HybridBlock, extract_pure_fn
    from mxnet_tpu.ndarray.ndarray import _apply
    from mxnet_tpu.models.faster_rcnn import FasterRCNN, rcnn_targets
    from mxnet_tpu.ops import detection_ops as D

    backbone = 50 if input_size >= 256 else 18
    post_nms = 128 if input_size >= 256 else 32
    n_samples = 64 if input_size >= 256 else 16
    net = FasterRCNN(num_classes=20, backbone_layers=backbone,
                     input_size=input_size, post_nms=post_nms)
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")  # same fp16-class basis as every sibling bench

    class _Train(HybridBlock):
        def __init__(self, inner, **kw):
            super().__init__(**kw)
            self.inner = inner

        def hybrid_forward(self, F, x, gt):
            obj, deltas, feat = self.inner(x)
            props, _ = self.inner.rpn_proposals(obj, deltas, pre_nms=512)
            # proposals/targets are detached constants in the reference's
            # training loop — without stop_gradient the box loss would
            # backprop through NMS/top_k/encode AND chase its own moving
            # targets (box_t depends on deltas)
            rois, cls_t, box_t, box_m = _apply(
                lambda p, g: jax.vmap(lambda pp, gg: rcnn_targets(
                    jax.lax.stop_gradient(pp), gg,
                    num_samples=n_samples))(p, g),
                [props, gt], n_out=4)
            cls, box = self.inner.roi_head(feat, rois)
            return obj, deltas, cls, box, cls_t, box_t, box_m

    wrap = _Train(net)
    x = mx.nd.random.uniform(shape=(batch, input_size, input_size, 3),
                             dtype="bfloat16")
    rng = np.random.RandomState(0)
    M = 8
    wh = rng.uniform(0.1, 0.3, (batch, M, 2)) * input_size
    xy = rng.uniform(0.0, 0.6, (batch, M, 2)) * input_size
    cls_lab = rng.randint(0, 20, (batch, M, 1)).astype(np.float32)
    gt = mx.nd.array(np.concatenate([cls_lab, xy, xy + wh], -1)
                     .astype(np.float32))
    wrap(x, gt)  # materialise params
    fwd, params = extract_pure_fn(wrap, x, gt, training=True)
    aux_idx = list(fwd.aux_indices)

    # RPN targets vs the static anchor grid, precomputed (label-only work)
    anchors_n = jnp.asarray(net.anchors, jnp.float32) / input_size
    gt_n = jnp.asarray(gt._data)
    gt_n = gt_n.at[:, :, 1:].set(gt_n[:, :, 1:] / input_size)
    # variances (1,1,1,1): generate_proposals decodes RPN deltas unscaled,
    # so the supervision must use the same encoding (r4 review finding)
    rpn_cls_t, rpn_box_t, rpn_box_m = D.multibox_target(
        anchors_n, gt_n, 0.5, variances=(1, 1, 1, 1))

    def loss_fn(p, xb, gtb, rct, rbt, rbm):
        (obj, deltas, cls, box, cls_t, box_t, box_m), aux = fwd(p, xb, gtb)
        obj = obj.astype(jnp.float32)
        rpn_obj_l = jnp.mean(
            jax.nn.log_sigmoid(jnp.where(rct > 0, obj, -obj)) * -1.0)
        d = (deltas.astype(jnp.float32) - rbt) * rbm
        rpn_box_l = jnp.mean(jnp.where(jnp.abs(d) < 1.0, 0.5 * d * d,
                                       jnp.abs(d) - 0.5))
        lp = jax.nn.log_softmax(cls.astype(jnp.float32), -1)
        rcnn_cls_l = -jnp.mean(jnp.take_along_axis(
            lp, cls_t.astype(jnp.int32)[..., None], -1))
        bsel = jnp.take_along_axis(
            box.astype(jnp.float32),
            cls_t.astype(jnp.int32)[..., None, None]
            .repeat(4, -1), -2)[..., 0, :]
        d2 = (bsel - box_t) * box_m
        rcnn_box_l = jnp.mean(jnp.where(jnp.abs(d2) < 1.0, 0.5 * d2 * d2,
                                        jnp.abs(d2) - 0.5))
        return rpn_obj_l + rpn_box_l + rcnn_cls_l + rcnn_box_l, aux

    from bench_util import make_sgd_step
    # lr 1e-3: the two-stage loss sees a SHIFTING proposal distribution
    # every step (rois follow the RPN), so the SSD bench's 0.01 oscillates
    step = make_sgd_step(loss_fn, aux_idx, lr=1e-3, mu=0.9,
                         unroll=unroll)
    mom = [jnp.zeros_like(p) for p in params]
    data = (x._data, gt._data, rpn_cls_t, rpn_box_t, rpn_box_m)
    if return_parts:
        return step, params, mom, data, (net, fwd)
    return step, params, mom, data


def _measure_rcnn(batch, steps, input_size):
    # perf lever (BENCH_DET_RCNN_UNROLL=k): k steps per dispatch, the
    # SSD/ResNet amortisation. Resolved HERE only — the convergence and
    # profile tools reuse build_rcnn_step and must keep 1 step = 1 step.
    unroll = max(1, int(os.environ.get("BENCH_DET_RCNN_UNROLL", "1")))
    step, params, mom, data = build_rcnn_step(batch, input_size,
                                              unroll=unroll)
    from bench_util import timed_measure
    return timed_measure(step, params, mom, data, steps, batch * unroll,
                         tag=f"bench_rcnn b{batch}")


def measure_rcnn(batch=None, steps=None, on_result=None):
    """Faster-RCNN-resnet50 train img/s (BASELINE config 5's second half).

    Denominator (BASELINE_RCNN_IMG_S = 270), defended: the backbone cost
    matches SSD's (~75 GFLOP/img train at 512^2) but the two-stage extra
    (proposal top-k/NMS, per-image target sampling, RoIAlign, the
    per-roi head) is gather/sort-bound, not MXU-bound. GluonCV's
    training-speed tables put SSD-512 and Faster-RCNN-resnet50 (1x,
    ~600-800px) at a per-GPU throughput ratio around 1.6-2:1 on the
    same V100 hardware. Dividing the (itself conservative) SSD
    denominator by the FAVOURABLE end of that ratio gives 420/1.6 ~=
    270; the 2:1 end would give 210. As with SSD we keep the higher
    number, so >=1.0x here clears the reference under either reading."""
    import jax

    on_tpu = jax.default_backend() == "tpu"
    candidates = ([8, 16] if on_tpu else [2]) if batch is None else (
        list(batch) if isinstance(batch, (list, tuple)) else [batch])
    if steps is None:
        steps = 10 if on_tpu else 2
    input_size = 512 if on_tpu else 128
    print(f"[bench_rcnn] backend={jax.default_backend()} "
          f"candidates={candidates} input={input_size} steps={steps}",
          file=sys.stderr)
    from bench_util import sweep

    def _res(v):
        return {"metric": "faster_rcnn_train_throughput",
                "value": round(v, 1), "unit": "images/sec/chip",
                "vs_baseline": round(v / BASELINE_RCNN_IMG_S, 4)}

    best, _ = sweep(candidates, 200,
                    lambda b: _measure_rcnn(b, steps, input_size),
                    on_best=None if on_result is None
                    else (lambda v: on_result(_res(v))),
                    tag="bench_rcnn")
    return _res(best)


def _measure_one(batch, steps, input_size):
    step, params, mom, data, unroll = build_step(batch, input_size)
    from bench_util import timed_measure
    return timed_measure(step, params, mom, data, steps, batch * unroll,
                         tag=f"bench_det b{batch}")


def measure(batch=None, steps=None, on_result=None):
    import jax

    on_tpu = jax.default_backend() == "tpu"
    if batch is None:
        candidates = [16, 32] if on_tpu else [2]
    else:
        candidates = list(batch) if isinstance(batch, (list, tuple)) \
            else [batch]
    if steps is None:
        steps = 10 if on_tpu else 2
    input_size = 512 if on_tpu else 128
    print(f"[bench_det] backend={jax.default_backend()} "
          f"candidates={candidates} input={input_size} steps={steps}",
          file=sys.stderr)

    from bench_util import sweep
    SWEEP_BUDGET_S = 200

    best, _ = sweep(candidates, SWEEP_BUDGET_S,
                    lambda b: _measure_one(b, steps, input_size),
                    on_best=None if on_result is None
                    else (lambda v: on_result(_result(v))),
                    tag="bench_det")
    return _result(best)


def _result(img_s):
    return {
        "metric": "ssd512_train_throughput",
        "value": round(img_s, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }


def main():
    # honor JAX_PLATFORMS=cpu despite the axon sitecustomize (same dance
    # as bench.py — jax.config wins if set before backend init)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    batch = os.environ.get("BENCH_DET_BATCH")
    steps = os.environ.get("BENCH_DET_STEPS")
    # standalone: BENCH_DET_RCNN=1 SELECTS the Faster-RCNN metric (one
    # JSON line per invocation); the bench.py driver's BENCH_DET=1 runs
    # both detectors and merges them as extra_metrics
    if os.environ.get("BENCH_DET_RCNN") == "1":
        res = measure_rcnn(
            [int(b) for b in batch.split(",")] if batch else None,
            int(steps) if steps else None)
    else:
        res = measure([int(b) for b in batch.split(",")] if batch else None,
                      int(steps) if steps else None)
    print(json.dumps(res))


if __name__ == "__main__":
    main()
