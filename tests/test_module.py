"""Module API tests (SURVEY.md §2 #13): bind/init/fit/predict/checkpoint."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, io as mio


def _softmax_mlp():
    data = sym.Variable("data")
    w1, b1 = sym.Variable("w1"), sym.Variable("b1")
    h = sym.Activation(sym.FullyConnected(data, w1, b1, num_hidden=16),
                       act_type="relu")
    w2, b2 = sym.Variable("w2"), sym.Variable("b2")
    out = sym.FullyConnected(h, w2, b2, num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"))


def _toy_iter(n=96, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 6).astype(np.float32)
    w = rng.randn(6, 3).astype(np.float32)
    y = np.argmax(x @ w, 1).astype(np.float32)
    return mio.NDArrayIter(x, y, batch_size=batch, label_name="softmax_label")


def test_bind_and_forward():
    mod = mx.mod.Module(_softmax_mlp(), data_names=("data",),
                        label_names=("softmax_label",))
    it = _toy_iter()
    mod.bind([(d.name, d.shape) for d in it.provide_data],
             [(l.name, l.shape) for l in it.provide_label])
    mod.init_params()
    batch = next(iter(it))
    mod.forward(batch)
    out = mod.get_outputs()[0]
    assert out.shape == (32, 3)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(32), rtol=1e-4)


def test_fit_converges():
    mod = mx.mod.Module(_softmax_mlp())
    it = _toy_iter()
    mod.fit(it, num_epoch=30, optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    m = mx.metric.Accuracy()
    mod.score(_toy_iter(), m)
    assert m.get()[1] > 0.8, m.get()


def test_predict():
    mod = mx.mod.Module(_softmax_mlp())
    it = _toy_iter()
    mod.fit(it, num_epoch=2)
    preds = mod.predict(_toy_iter())
    assert preds.shape[0] == 96


def test_save_load_checkpoint():
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "mod")
        mod = mx.mod.Module(_softmax_mlp())
        it = _toy_iter()
        mod.fit(it, num_epoch=2)
        mod.save_checkpoint(prefix, 2)
        arg1, _ = mod.get_params()
        mod2 = mx.mod.Module.load(prefix, 2)
        it2 = _toy_iter()
        mod2.bind([(dd.name, dd.shape) for dd in it2.provide_data],
                  [(l.name, l.shape) for l in it2.provide_label])
        mod2.init_params(arg_params=mod2._loaded_params[0],
                         aux_params=mod2._loaded_params[1])
        arg2, _ = mod2.get_params()
        for k in arg1:
            np.testing.assert_allclose(arg1[k].asnumpy(), arg2[k].asnumpy(),
                                       rtol=1e-5)
