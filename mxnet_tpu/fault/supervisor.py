"""Self-healing training: a crash-only recovery supervisor.

PR 3 built the reflexes (injection, retry, watchdog, preemption-safe
checkpoints) and PR 8 the elastic resharding — but nothing connected
detect → diagnose → recover: a failed step still killed the process.
`TrainingSupervisor` (or the `fault.run_supervised` convenience) closes
the loop as a crash-only state machine around the training loop:

    RUN ──failure──▶ CLASSIFY ──▶ RECOVER(domain) ──▶ RUN

Every failure lands in one of the recovery domains, each with a policy:

  ================  ==========================  =======================
  domain            detected by                 recovery (parity)
  ================  ==========================  =======================
  transient         any retryable step error    retry the SAME batch via
                                                `RetryPolicy` (bitwise)
  corrupt_state     non-finite loss, loss       rollback to last VALID +
                    divergence                  HEALTHY checkpoint, replay
                                                the data stream (bitwise)
  hang              `WatchdogTimeout`,          watchdog post-mortem,
                    `kvstore.CollectiveTimeout` bounded engine drain, then
                                                rollback + replay (bitwise)
  capacity_loss     `DeviceLost`                shrink the mesh to the
                    (device.lost fault point)   survivors via
                                                `Trainer.resize_mesh` and
                                                continue sharded (NOT
                                                bitwise: reduction
                                                geometry changes)
  preemption        SIGTERM / `Preempted`       emergency save (armed on
                                                the CheckpointManager) →
                                                resumable exit (bitwise
                                                across the restart)
  capacity_gain     capacity probe: every       regrow the mesh back to
                    pre-shrink device is back   its pre-shrink shape via
                    (hysteresis + cooldown      `Trainer.resize_mesh`
                    guarded)                    (collective-only; restart
                                                budget refilled; NOT
                                                bitwise across the
                                                geometry change)
  host_lost         `HostLost` (host.lost       fleet rollback agreement
                    fault point / dead peer     (fault/fleet.py); without
                    heartbeat via               a fleet: rollback +
                    `FleetSupervisor`)          replay like hang
  ================  ==========================  =======================

Rollback + replay is deterministic: the periodic checkpoint records the
number of batches consumed (`supervisor.json` extra) beside the params,
optimizer state (`Trainer.states_bytes`) and a HEALTH verdict
(`checkpoint.HEALTH_NAME`); recovery restores the newest valid+healthy
step (`restore_latest_healthy` — an intact checkpoint written mid-NaN-
storm is skipped) and fast-forwards a freshly built data iterator by the
recorded batch count, so the recovered trajectory is bitwise-equal to a
fault-free run (given a replayable data factory and a step function with
no hidden host state).

Escalation is bounded: each recovery consumes one unit of
`restart_budget` with exponential backoff between incidents; a window of
clean progress (`budget_reset_steps` applied steps) restores the full
budget. Exhausting it writes a structured CRASH REPORT (incidents,
domains, engine pending report, metrics snapshot) and raises
`RecoveryExhausted` — the process-level supervisor's cue that in-process
recovery is out of moves.

Observability: ``fault_recoveries{domain=}``,
``fault_restart_budget_remaining``, ``fault_crash_reports``, and one
trace instant per incident. The chaos soak `tools/check_resilience.py`
drives every domain in tier-1; knobs and parity promises are documented
in docs/RELIABILITY.md "Recovery playbook".
"""
from __future__ import annotations

import json
import math
import os
import time

from ..base import MXNetError
from ..observability import registry as _obs_registry
from ..observability import tracer as _tracer
from .. import _env
from . import injection as _finj
from .injection import DeviceLost, HostLost
from .preemption import Preempted, check_preempted
from .retry import RetryPolicy
from .watchdog import StepWatchdog, WatchdogTimeout, _warn_unwritable

__all__ = ["DOMAINS", "TrainingSupervisor", "run_supervised",
           "RecoveryExhausted", "NonFiniteLoss", "DivergedLoss",
           "classify_failure"]

DOMAINS = ("transient", "corrupt_state", "hang", "capacity_loss",
           "preemption", "capacity_gain", "host_lost")

META_NAME = "supervisor.json"      # per-checkpoint replay cursor extra
STATES_NAME = "trainer.states"     # per-checkpoint optimizer-state extra
INCIDENTS_NAME = "incidents.jsonl"  # per-incident JSONL in the crash dir

_reg = _obs_registry()
_budget_gauge = _reg.gauge("fault_restart_budget_remaining")
_crash_counter = _reg.counter("fault_crash_reports")
_regrow_counter = _reg.counter("fault_regrows")


def _count_recovery(domain):
    # cold failure path: the registry's own (name, labels) memo is the
    # cache — no hand-rolled handle dict needed here
    _reg.counter("fault_recoveries", domain=domain).inc()


def _log():
    from ..log import get_logger
    return get_logger("mxnet_tpu.fault")


class RecoveryExhausted(MXNetError):
    """The restart budget ran out (or a domain had no viable recovery).
    `.report` holds the structured crash report; `.report_path` names
    the JSON on disk (None when the crash dir was unwritable)."""

    def __init__(self, msg, report=None, report_path=None):
        self.report = report
        self.report_path = report_path
        super().__init__(msg)


class NonFiniteLoss(MXNetError):
    """The recorded loss went inf/NaN — corrupt-state domain."""


class DivergedLoss(MXNetError):
    """The recorded loss exploded against its rolling window —
    corrupt-state domain."""


def classify_failure(exc):
    """Map one failure to its recovery domain (the default `classify`
    hook). Anything unrecognised is TRANSIENT — the safest default: a
    retry is cheap, and a persistently failing step escalates to
    rollback and then the restart budget anyway."""
    from ..kvstore import CollectiveTimeout
    if isinstance(exc, Preempted):
        return "preemption"
    if isinstance(exc, HostLost):
        return "host_lost"
    if isinstance(exc, DeviceLost):
        return "capacity_loss"
    if isinstance(exc, (WatchdogTimeout, CollectiveTimeout)):
        return "hang"
    if isinstance(exc, (NonFiniteLoss, DivergedLoss)):
        return "corrupt_state"
    return "transient"


class _NonTransient(BaseException):
    """Carrier lifting a non-transient failure OVER the RetryPolicy
    (which retries `Exception` subclasses): a hang or device loss must
    reach its own domain policy, not burn step retries."""

    def __init__(self, exc):
        self.exc = exc
        super().__init__(repr(exc))


class _ReplayCursor:
    """Deterministic batch stream with seek: wraps a zero-arg factory
    (or a re-iterable collection) and counts batches drawn; `seek(n)`
    rebuilds the stream and re-draws n batches so a rollback replays the
    exact fault-free sequence (epoch wrap included). A bare one-shot
    iterator still trains but refuses seek — rollback/resume need a
    replayable source."""

    def __init__(self, data):
        if callable(data):
            self._factory = data
        elif hasattr(data, "__next__"):
            self._factory = None          # consumed-once: not replayable
            self._one_shot = data
        else:
            self._factory = lambda: iter(data)
        self._it = None
        self.drawn = 0

    @property
    def replayable(self):
        return self._factory is not None

    def _fresh(self):
        if self._factory is not None:
            return iter(self._factory())
        it, self._one_shot = self._one_shot, None
        if it is None:
            raise MXNetError("data iterator already consumed and not "
                             "replayable; pass a zero-arg factory")
        return it

    def next(self):
        if self._it is None:
            self._it = self._fresh()
        try:
            batch = next(self._it)
        except StopIteration:
            if not self.replayable:
                raise
            self._it = self._fresh()      # epoch wrap
            batch = next(self._it)        # empty stream: let it propagate
        self.drawn += 1
        return batch

    def seek(self, n):
        if not self.replayable:
            raise MXNetError(
                "rollback/resume needs a replayable data source — pass a "
                "zero-arg iterator factory (or a re-iterable dataset) to "
                "the supervisor, not a half-consumed iterator")
        self._it = self._fresh()
        self.drawn = 0
        for _ in range(int(n)):
            self.next()


class TrainingSupervisor:
    """Crash-only recovery supervisor around a training loop.

    trainer:  the `gluon.Trainer` whose params/optimizer state define
              the recoverable state (default snapshot/restore hooks read
              them structurally; override with params_fn/set_params_fn).
    step_fn:  `step_fn(batch) -> loss` — runs ONE training step and
              returns a loss (anything `float(np.asarray(...))` accepts).
              Must be repeat-safe until the update applies: a failure
              before the optimizer update may be retried on the same
              batch (the imperative and captured steps both qualify).
    data:     zero-arg iterator factory (replayable → rollback/resume
              work), a re-iterable dataset, or a bare iterator
              (trainable, but rollback refuses).

    checkpoint_dir/manager: where periodic + emergency checkpoints live;
              None disables checkpointing (then corrupt-state/hang
              failures go straight to the crash report).
    checkpoint_every: periodic save cadence in applied steps.
    restart_budget: recoveries allowed before the crash report;
              `budget_reset_steps` clean applied steps restore it.
    check_every: loss health-check cadence (finiteness + divergence).
    divergence_factor: loss > factor * max(1, |median(window)|) raises
              `DivergedLoss` (needs >= 4 recorded losses).
    retry:    `RetryPolicy` for in-step transient retries (None → a
              default 3-attempt policy; retries are counted in
              ``fault_retries{site=supervisor_step}`` and do NOT consume
              restart budget — exhausting them escalates to rollback,
              which does).
    """

    def __init__(self, trainer, step_fn, data, *, checkpoint_dir=None,
                 manager=None, checkpoint_every=10, max_to_keep=3,
                 restart_budget=5, budget_reset_steps=64,
                 backoff_base=0.05, backoff_max=5.0, retry=None,
                 check_every=1, divergence_factor=1e4, health_window=16,
                 watchdog=None, crash_dir=None, classify=None,
                 on_capacity_loss=None, params_fn=None, set_params_fn=None,
                 emergency_save=True, drain_timeout_ms=2000,
                 regrow_cooldown=None, regrow_hysteresis=None,
                 sleep=time.sleep):
        from ..checkpoint import CheckpointManager
        self._trainer = trainer
        self._step_fn = step_fn
        self._cursor = _ReplayCursor(data)
        if manager is None and checkpoint_dir is not None:
            manager = CheckpointManager(checkpoint_dir,
                                        max_to_keep=max_to_keep)
        self._mgr = manager
        self.checkpoint_every = int(checkpoint_every)
        self.restart_budget = int(restart_budget)
        self.budget_reset_steps = int(budget_reset_steps)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._retry = retry if retry is not None else RetryPolicy(
            max_retries=3, base_delay=0.02, max_delay=0.5,
            name="supervisor_step")
        self.check_every = max(1, int(check_every))
        self.divergence_factor = float(divergence_factor)
        self.health_window = int(health_window)
        self._watchdog = watchdog if watchdog is not None else \
            StepWatchdog()
        self._crash_dir = crash_dir or os.environ.get(
            "MXTPU_CRASH_DIR", self._watchdog.snapshot_dir)
        self._classify = classify or classify_failure
        self._on_capacity_loss = on_capacity_loss
        self._params_fn = params_fn or self._default_params
        self._set_params_fn = set_params_fn or self._default_set_params
        self._emergency = bool(emergency_save) and self._mgr is not None
        self.drain_timeout_ms = int(drain_timeout_ms)
        self._sleep = sleep

        self._applied = 0             # updates applied (= batches consumed)
        self._pending_batch = None    # drawn but not yet applied
        self._losses = []             # rolling float window
        self._budget_left = self.restart_budget
        self._consec_incidents = 0
        self._steps_since_incident = 0
        self._incidents = []          # structured incident log
        self.recoveries = {d: 0 for d in DOMAINS}
        # grow-back: a shrink records the pre-shrink layout; the per-step
        # capacity probe regrows once every lost device is back, guarded
        # by hysteresis (consecutive clean probes) and a cooldown (applied
        # steps since the shrink / last failed regrow) against capacity
        # flapping re-resharding the job every few steps
        self.regrow_cooldown = int(regrow_cooldown) \
            if regrow_cooldown is not None \
            else _env.env_int("MXTPU_REGROW_COOLDOWN_STEPS", 8, minimum=0)
        self.regrow_hysteresis = max(1, int(regrow_hysteresis)) \
            if regrow_hysteresis is not None \
            else _env.env_int("MXTPU_REGROW_HYSTERESIS", 2, minimum=1)
        self._pre_shrink = None       # {"axes", "devices", "lost"}
        self._regrow_ready = 0        # consecutive capacity-clean probes
        self._regrow_wait_from = 0    # cooldown anchor (applied steps)
        _budget_gauge.set(self._budget_left)

    # --------------------------------------------- default state hooks
    def _default_params(self):
        """Structural-keyed jax-array snapshot of the trainer's params
        (auto-names drift across in-process rebuilds; positions don't)."""
        import jax.numpy as jnp
        return {f"p{i:03d}": jnp.asarray(p._data._data)
                for i, p in enumerate(self._trainer._params)
                if p._data is not None}

    def _default_set_params(self, tree):
        from ..ndarray.ndarray import NDArray
        for i, p in enumerate(self._trainer._params):
            if p._data is None:
                continue
            arr = tree[f"p{i:03d}"]
            p.set_data(NDArray(getattr(arr, "_data", arr)))

    def _template(self):
        """Restore template from the LIVE params — the template's
        sharding wins at restore, so a rule-sharded trainer restores
        straight back onto its current mesh layout."""
        return self._params_fn()

    # ------------------------------------------------- state snapshots
    def _meta_blob(self):
        return json.dumps({"applied": self._applied,
                           "loss_window": self._losses[-self.health_window:],
                           "time": time.time()}).encode()

    def _extras(self):
        return {META_NAME: self._meta_blob(),
                STATES_NAME: self._trainer.states_bytes()}

    def health_record(self, params=None):
        """The last-known-good journal entry for the CURRENT rolling
        window (written with every periodic and emergency save). Besides
        the loss stats it checks the PARAMS themselves for finiteness: a
        NaN that poisoned the weights at step k only shows in the loss
        at k+1, so a checkpoint saved between the two would otherwise be
        journalled healthy while holding garbage. `params` lets the
        caller pass an already-materialised snapshot (the periodic save
        shares one with the payload instead of snapshotting twice)."""
        window = self._losses[-self.health_window:]
        finite = all(math.isfinite(v) for v in window)
        diverged = self._diverged(window)
        params_finite = self._params_finite(params)
        return {"applied": self._applied,
                "loss": window[-1] if window else None,
                "finite": finite, "diverged": diverged,
                "params_finite": params_finite,
                "window": len(window),
                "healthy": finite and not diverged and params_finite}

    def _params_finite(self, params=None):
        import jax.numpy as jnp
        try:
            leaves = [getattr(v, "_data", v)
                      for v in (params if params is not None
                                else self._params_fn()).values()]
            if not leaves:
                return True
            # one stacked reduction -> ONE host sync for the whole tree
            return bool(jnp.all(jnp.stack(
                [jnp.isfinite(a).all() for a in leaves])))
        except Exception:
            return True    # exotic leaves: fall back to loss stats only

    def _diverged(self, window):
        if len(window) < 4 or not all(math.isfinite(v) for v in window):
            return False
        prior = sorted(window[:-1])
        median = prior[len(prior) // 2]
        return window[-1] > self.divergence_factor * max(1.0, abs(median))

    def _save_checkpoint(self):
        params = self._params_fn()
        self._mgr.save(self._applied, params, extras=self._extras(),
                       health=self.health_record(params=params))

    # ------------------------------------------------------- main loop
    def run(self, num_steps, resume=None):
        """Drive `num_steps` applied training steps under supervision.
        `resume=None` auto-resumes when the checkpoint dir already holds
        steps (the restart half of a preemption). Returns a report dict:
        ``outcome`` ("completed" | "preempted" | "data_exhausted" — the
        last only for non-replayable sources that ran dry), ``applied``,
        ``final_loss``, ``incidents``, ``recoveries``,
        ``budget_remaining``, ``resumed_from``. Raises
        `RecoveryExhausted` (after writing the crash report) when the
        restart budget runs out."""
        resumed_from = None
        outcome = "completed"
        self._arm()
        try:
            if resume is None:
                resume = self._mgr is not None and bool(self._mgr.steps())
            if resume:
                resumed_from = self._restore(initial=True)
            elif self._mgr is not None and self._mgr.steps():
                # resume=False over a dir that already holds steps is a
                # foreign-state trap: a later ROLLBACK would scan the
                # whole dir and restore the old run's newest healthy
                # step — silently splicing two unrelated runs. Refuse
                # the ambiguity instead.
                raise MXNetError(
                    f"supervisor: resume=False but checkpoint dir "
                    f"{self._mgr.directory!r} already holds steps "
                    f"{self._mgr.steps()} — a rollback would restore "
                    f"that foreign state; pass resume=True to continue "
                    f"it, or point at a fresh directory")
            if self._mgr is not None and resumed_from is None:
                # step-0 last-known-good: rollback must NEVER be
                # impossible — a hang on the very first step restores
                # here and replays from the top (still bitwise)
                self._save_checkpoint()
            while self._applied < num_steps:
                try:
                    if _finj.ENABLED:
                        _finj.check("preempt.sigterm", context="supervisor")
                        _finj.check_device_loss(
                            context=f"step {self._applied}")
                    check_preempted()
                    if self._pending_batch is None:
                        try:
                            self._pending_batch = self._cursor.next()
                        except StopIteration:
                            # a one-shot iterator ran dry (or the stream
                            # is empty): end of DATA, not a failure —
                            # routing it through recovery would burn the
                            # restart budget on a non-fault
                            outcome = "data_exhausted"
                            _log().warning(
                                "supervisor: data source exhausted after "
                                "%d applied steps (requested %d) — "
                                "stopping", self._applied, num_steps)
                            break
                    loss = self._attempt_step(self._pending_batch)
                    self._pending_batch = None
                    self._applied += 1
                    self._record_loss(loss)
                    self._note_progress()
                    self._probe()
                    if self._mgr is not None and self.checkpoint_every and \
                            self._applied % self.checkpoint_every == 0:
                        self._save_checkpoint()
                    if self._applied % self.check_every == 0:
                        self._health_check()
                    if self._watchdog.enabled:
                        self._watchdog.check(step=self._applied)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Preempted as e:
                    # the emergency save (armed below) already ran inside
                    # the signal handler; leave a resumable trail and exit
                    # — counted like every other domain recovery so
                    # dashboards see real preemptions, not only
                    # custom-classified ones
                    outcome = "preempted"
                    incident = {"domain": "preemption",
                                "applied": self._applied,
                                "error": repr(e), "recovered": True,
                                "time": time.time()}
                    self._incidents.append(incident)
                    self._emit_incident(incident)
                    self.recoveries["preemption"] += 1
                    _count_recovery("preemption")
                    _log().warning(
                        "supervisor: preempted after %d applied steps; "
                        "emergency checkpoint %s — exiting resumable",
                        self._applied,
                        "written" if self._emergency else "NOT armed")
                    break
                except BaseException as e:
                    if self._recover(e) == "preempted":
                        # a classify hook mapped a custom preemption
                        # notice here: _recover already saved the
                        # resumable checkpoint
                        outcome = "preempted"
                        _log().warning(
                            "supervisor: classified preemption after %d "
                            "applied steps; checkpoint written — exiting "
                            "resumable", self._applied)
                        break
        finally:
            self._disarm()
        return {"outcome": outcome, "applied": self._applied,
                "final_loss": self._losses[-1] if self._losses else None,
                "incidents": list(self._incidents),
                "recoveries": dict(self.recoveries),
                "budget_remaining": self._budget_left,
                "resumed_from": resumed_from}

    # -------------------------------------------------- step execution
    def _attempt_step(self, batch):
        """One step under the transient RetryPolicy: retryable failures
        re-run the SAME batch (bitwise — the optimizer update never
        applied); non-transient failures lift straight out to their
        domain policy. An in-step retry that SUCCEEDS counts as a
        recovered transient incident but consumes no restart budget
        (the RetryPolicy itself bounds it)."""
        attempts = [0]
        last_err = [None]

        def once():
            attempts[0] += 1
            try:
                return self._step_fn(batch)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:
                last_err[0] = e
                if isinstance(e, Exception) and \
                        self._classify(e) == "transient":
                    raise
                raise _NonTransient(e) from e

        try:
            result = self._retry.call(once)
        except _NonTransient as carrier:
            raise carrier.exc
        if attempts[0] > 1:
            incident = {"domain": "transient", "applied": self._applied,
                        "error": repr(last_err[0]),
                        "retries": attempts[0] - 1,
                        "recovered": True, "time": time.time()}
            self._incidents.append(incident)
            self._emit_incident(incident)
            self.recoveries["transient"] += 1
            _count_recovery("transient")
            if _tracer.ACTIVE:
                _tracer.instant("fault.incident", cat="fault",
                                args={"domain": "transient",
                                      "applied": self._applied,
                                      "retries": attempts[0] - 1})
        return result

    def _record_loss(self, loss):
        import numpy as np
        try:
            # .item() accepts any size-1 array shape ((), (1,), (1,1));
            # float(ndarray) on ndim>0 is deprecated and will raise
            value = float(np.asarray(getattr(loss, "_data", loss)).item())
        except (TypeError, ValueError) as e:
            raise MXNetError(
                f"supervisor: step_fn must return a scalar-coercible "
                f"loss (got {type(loss).__name__}): {e}") from e
        self._losses.append(value)
        if len(self._losses) > 4 * self.health_window:
            del self._losses[:-2 * self.health_window]

    def _health_check(self):
        window = self._losses[-self.health_window:]
        if not window:
            return
        if not math.isfinite(window[-1]):
            raise NonFiniteLoss(
                f"loss {window[-1]} at applied step {self._applied} — "
                f"parameters are likely poisoned; rolling back")
        if self._diverged(window):
            raise DivergedLoss(
                f"loss {window[-1]:g} exploded past "
                f"{self.divergence_factor:g}x the rolling median at "
                f"applied step {self._applied}; rolling back")

    def _note_progress(self):
        self._steps_since_incident += 1
        if self._consec_incidents and \
                self._steps_since_incident >= self.budget_reset_steps:
            self._consec_incidents = 0
            if self._budget_left < self.restart_budget:
                _log().info(
                    "supervisor: %d clean steps — restart budget restored "
                    "to %d", self._steps_since_incident, self.restart_budget)
                self._budget_left = self.restart_budget
                _budget_gauge.set(self._budget_left)

    # ------------------------------------------------- incident records
    def incidents(self):
        """The structured incident log, oldest first: one dict per
        incident ({"domain", "applied", "error"/"axes", "recovered",
        "time", ...}). Every recovery — successful or not — lands here;
        successful ones are ALSO appended as JSON lines to
        ``incidents.jsonl`` in the crash dir, so a run that never
        exhausts its budget still leaves an on-disk trail."""
        return list(self._incidents)

    def _emit_incident(self, incident):
        """Best-effort one-line JSONL append in the crash dir. Crash-only
        discipline: an unwritable dir degrades to the in-memory log (and
        the eventual crash report), never a secondary failure."""
        try:
            os.makedirs(self._crash_dir, exist_ok=True)
            with open(os.path.join(self._crash_dir, INCIDENTS_NAME),
                      "a") as f:
                f.write(json.dumps(incident, default=str) + "\n")
        except OSError as e:
            _warn_unwritable(self._crash_dir, e)

    # ------------------------------------------------- capacity probe
    def _probe(self):
        """Per-applied-step probe hook, called once after every clean
        step (inside the supervised try block, so anything it raises
        routes through CLASSIFY → RECOVER like a step failure). The base
        implementation runs the grow-back capacity probe; the fleet
        supervisor (fault/fleet.py) extends it with heartbeats and peer
        liveness."""
        self._maybe_regrow()

    def _maybe_regrow(self):
        """Grow-back: when every device the shrink lost is back in the
        active set (unmasked from `injection.lost_devices`), reverse the
        shrink via `Trainer.resize_mesh` to the recorded pre-shrink
        layout. Hysteresis demands `regrow_hysteresis` CONSECUTIVE clean
        probes and the cooldown `regrow_cooldown` applied steps since
        the shrink (or the last failed regrow) — both guard against
        capacity flapping thrashing the job through resharding. Returns
        True when a regrow happened."""
        pre = self._pre_shrink
        if pre is None:
            return False
        if self._applied - self._regrow_wait_from < self.regrow_cooldown:
            return False
        still_lost = set(_finj.lost_devices())
        if any(d in still_lost for d in pre["lost"]):
            self._regrow_ready = 0
            return False
        self._regrow_ready += 1
        if self._regrow_ready < self.regrow_hysteresis:
            return False
        return self._regrow(pre)

    def _regrow(self, pre):
        import jax
        incident = {"domain": "capacity_gain", "applied": self._applied,
                    "axes": dict(pre["axes"]),
                    "devices": list(pre["devices"]), "time": time.time()}
        by_id = {d.id: d for d in jax.devices()}
        try:
            devices = [by_id[i] for i in pre["devices"]]
            self._trainer.resize_mesh(dict(pre["axes"]), devices=devices)
        except Exception as e:
            # a failed regrow is NOT fatal: the job keeps training on
            # the shrunk mesh (which works), consumes no restart budget,
            # and re-probes after a fresh cooldown
            incident["error"] = repr(e)
            incident["recovered"] = False
            self._incidents.append(incident)
            self._emit_incident(incident)
            self._regrow_ready = 0
            self._regrow_wait_from = self._applied
            _log().warning(
                "supervisor: regrow to %s failed (%r) — staying on the "
                "shrunk mesh, re-probing after %d steps", pre["axes"], e,
                self.regrow_cooldown)
            return False
        incident["recovered"] = True
        self._incidents.append(incident)
        self._emit_incident(incident)
        self.recoveries["capacity_gain"] += 1
        _count_recovery("capacity_gain")
        _regrow_counter.inc()
        if _tracer.ACTIVE:
            _tracer.instant("fault.regrow", cat="fault",
                            args={"applied": self._applied,
                                  "axes": dict(pre["axes"])})
        self._pre_shrink = None
        self._regrow_ready = 0
        self._regrow_wait_from = self._applied
        # the job is whole again: a regrow ENDS the degraded episode the
        # shrink opened, so the restart budget refills like a clean-
        # progress window would
        self._consec_incidents = 0
        if self._budget_left < self.restart_budget:
            self._budget_left = self.restart_budget
            _budget_gauge.set(self._budget_left)
        _log().warning(
            "supervisor: capacity returned — regrew mesh to %s over "
            "devices %s at applied step %d (restart budget restored to "
            "%d)", pre["axes"], pre["devices"], self._applied,
            self.restart_budget)
        return True

    # ----------------------------------------------------- recoveries
    def _host_lost_recover(self, exc):
        """Host-loss policy WITHOUT a fleet: a peer (or this process's
        own injected death) left mid-collective, so the collective
        stream is poisoned exactly like a hang — rollback to
        last-known-good and replay. `FleetSupervisor` overrides this
        with the cross-host rollback agreement."""
        self._rollback(exc, "host_lost")

    def _recover(self, exc):
        domain = self._classify(exc)
        if domain not in DOMAINS:
            # a custom classify hook returned something off-table:
            # treat as transient (the safe catch-all) rather than
            # KeyError'ing after the recovery already ran
            _log().warning("supervisor: classify hook returned unknown "
                           "domain %r — treating as transient", domain)
            domain = "transient"
        incident = {"domain": domain, "applied": self._applied,
                    "error": repr(exc), "time": time.time()}
        self._incidents.append(incident)
        if _tracer.ACTIVE:
            _tracer.instant("fault.incident", cat="fault",
                            args={"domain": domain,
                                  "applied": self._applied,
                                  "error": repr(exc)[:200]})
        if domain == "preemption":
            # a custom classify hook mapped its cluster's preemption
            # notice here without a SIGTERM ever being delivered (the
            # built-in Preempted never reaches _recover): the policy is
            # emergency save + resumable exit, NOT rollback — and it
            # consumes no restart budget
            if self._mgr is not None:
                self._save_checkpoint()
            incident["recovered"] = True
            self._emit_incident(incident)
            self.recoveries[domain] += 1
            _count_recovery(domain)
            return "preempted"
        if self._budget_left <= 0:
            self._crash(exc, domain, "restart budget exhausted")
        self._budget_left -= 1
        _budget_gauge.set(self._budget_left)
        self._consec_incidents += 1
        self._steps_since_incident = 0
        delay = min(self.backoff_max,
                    self.backoff_base * 2 ** (self._consec_incidents - 1))
        _log().warning(
            "supervisor: %s failure at applied step %d (%r) — recovering "
            "(budget %d/%d left, backoff %.3fs)", domain, self._applied,
            exc, self._budget_left, self.restart_budget, delay)
        if delay > 0:
            self._sleep(delay)
        if domain == "capacity_loss":
            self._shrink_mesh(exc)
        elif domain == "host_lost":
            self._host_lost_recover(exc)
        elif domain == "hang":
            self._hang_post_mortem(exc)
            self._rollback(exc, domain)
        else:
            # corrupt_state, and transient steps that exhausted their
            # in-step retries: the state may already be poisoned — the
            # only sound move is rollback to last-known-good + replay
            self._rollback(exc, domain)
        incident["recovered"] = True
        self._emit_incident(incident)
        self.recoveries[domain] += 1
        _count_recovery(domain)
        return "recovered"

    def _hang_post_mortem(self, exc):
        """The multi-controller hang answer: dump the post-mortem (what
        wedged, what was queued behind it), then a BOUNDED engine drain +
        failure reset so the in-process restart starts from a quiet
        engine instead of inheriting the wedge."""
        from .. import engine
        path = getattr(exc, "snapshot_path", None)
        if path is None:    # WatchdogTimeout already wrote its own
            path = self._watchdog.dump_snapshot(
                step=self._applied, reason=f"hang recovery: {exc!r}")
        if path:
            _log().warning("supervisor: hang post-mortem at %s", path)
        engine.wait_for_all_timeout(self.drain_timeout_ms)
        engine.clear_failures()

    def _rollback(self, exc, domain):
        if self._mgr is None:
            self._crash(exc, domain, "no checkpoint manager configured — "
                                     "rollback impossible")
        self._restore(initial=False, cause=exc, domain=domain)

    def _restore(self, initial, cause=None, domain=None):
        """Restore the newest valid+HEALTHY checkpoint and fast-forward
        the data stream to its recorded cursor. Returns the restored
        step, or None on an initial start with an empty dir."""
        step, params = self._mgr.restore_latest_healthy(self._template())
        if step is None:
            if initial:
                return None
            self._crash(cause, domain or "corrupt_state",
                        "no restorable checkpoint for rollback")
        self._apply_restored(step, params, cause=cause, domain=domain)
        return step

    def _apply_restored(self, step, params, cause=None, domain=None):
        """Install an already-loaded checkpoint (params + optimizer
        states + replay cursor) as the live training state. Shared by
        the rollback path and the fleet's restore-a-specific-step path
        (fault/fleet.py)."""
        self._set_params_fn(params)
        meta = {}
        raw = self._mgr.read_extra(step, META_NAME)
        if raw:
            try:
                meta = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                meta = {}
        states = self._mgr.read_extra(step, STATES_NAME)
        if states:
            self._trainer.load_states_bytes(states)
        applied = int(meta.get("applied", step))
        try:
            self._cursor.seek(applied)
        except MXNetError as e:
            # a non-replayable source makes rollback impossible — that
            # is a recovery dead end like any other: crash report +
            # RecoveryExhausted, not a bare error escaping run()
            self._crash(cause or e, domain or "corrupt_state",
                        f"rollback impossible: {e}")
        self._applied = applied
        self._pending_batch = None
        self._losses = [v for v in meta.get("loss_window", [])
                        if isinstance(v, (int, float))]
        _log().warning("supervisor: restored checkpoint step %s "
                       "(applied=%d) and replayed the data stream", step,
                       applied)

    def _shrink_mesh(self, exc):
        """Capacity loss: rebuild the mesh over the survivors and keep
        training sharded — collective-only redistribution
        (`Trainer.resize_mesh`), no rollback, params ride live. Parity
        is NOT promised across a shrink (the reduction geometry
        changes); determinism within the new mesh is."""
        lost = set(_finj.lost_devices())
        dev = getattr(exc, "device", None)
        if dev is not None:
            lost.add(int(dev))
        if self._on_capacity_loss is not None:
            self._on_capacity_loss(self._trainer, sorted(lost))
            return
        plan = getattr(self._trainer, "shard_plan", None)
        if plan is None:
            self._crash(exc, "capacity_loss",
                        "capacity loss without a shard plan — nothing to "
                        "shrink (attach one via Trainer.shard, or pass "
                        "on_capacity_loss)")
        # survivors of the CURRENT mesh: a lost chip shrinks the mesh it
        # belonged to; drafting idle spare devices is a grow decision the
        # on_capacity_loss hook can make, not a default
        survivors = [d for d in plan.mesh.devices.flatten()
                     if d.id not in lost]
        axes = dict(plan.mesh.shape)
        other = 1
        for name, size in axes.items():
            if name != plan.data_axis:
                other *= int(size)
        new_dp = len(survivors) // other
        if new_dp < 1:
            self._crash(exc, "capacity_loss",
                        f"only {len(survivors)} devices survive but the "
                        f"non-data axes need {other} — cannot shrink")
        # record the pre-shrink layout so the capacity probe can reverse
        # this exact resize when the lost devices return. A SECOND shrink
        # keeps the ORIGINAL layout as the regrow target (the job should
        # come all the way back) and extends the lost set.
        if self._pre_shrink is None:
            self._pre_shrink = {
                "axes": {k: int(v) for k, v in plan.mesh.shape.items()},
                "devices": [int(d.id)
                            for d in plan.mesh.devices.flatten()],
                "lost": sorted(lost)}
        else:
            self._pre_shrink["lost"] = sorted(
                set(self._pre_shrink["lost"]) | {int(d) for d in lost})
        self._regrow_ready = 0
        self._regrow_wait_from = self._applied
        axes[plan.data_axis] = new_dp
        self._trainer.resize_mesh(axes,
                                  devices=survivors[:new_dp * other])
        _log().warning(
            "supervisor: lost device(s) %s — resharded onto %d survivors "
            "(%s) and continuing", sorted(lost), new_dp * other, axes)

    # ---------------------------------------------------- crash report
    def _crash(self, exc, domain, reason):
        """Out of moves: write the structured crash report and raise
        `RecoveryExhausted`. Crash-only to the end — an unwritable crash
        dir degrades to the in-exception report, never a second crash."""
        from .. import engine
        report = {
            "time": time.time(),
            "reason": reason,
            "domain": domain,
            "error": repr(exc),
            "applied": self._applied,
            "restart_budget": self.restart_budget,
            "budget_remaining": self._budget_left,
            "incidents": list(self._incidents),
            "recoveries": dict(self.recoveries),
            "lost_devices": _finj.lost_devices(),
            "engine_pending": engine.pending_report(),
            "engine_failures": engine.failures(),
            "metrics": _reg.snapshot(),
        }
        _crash_counter.inc()
        if _tracer.ACTIVE:
            _tracer.instant("fault.crash_report", cat="fault",
                            args={"domain": domain, "reason": reason})
        path = None
        try:
            os.makedirs(self._crash_dir, exist_ok=True)
            path = os.path.join(
                self._crash_dir,
                f"crash-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
                f".json")
            with open(path, "w") as f:
                json.dump(report, f, indent=1, default=str)
        except OSError as e:
            _warn_unwritable(self._crash_dir, e)
            path = None
        raise RecoveryExhausted(
            f"supervisor: {reason} ({domain} failure at applied step "
            f"{self._applied}: {exc!r}); crash report: "
            f"{path or 'unwritable — embedded in this exception'}",
            report=report, report_path=path) from exc

    # ------------------------------------------------ arm/disarm hooks
    def _arm(self):
        if not self._emergency:
            return
        # one snapshot serves both the payload and the health verdict —
        # the emergency save runs inside the preemption grace window,
        # where a second full param materialisation can cost the
        # checkpoint (CheckpointManager materialises params_fn() before
        # health_fn() for exactly this sharing)
        snap = {}

        def params_fn():
            snap["params"] = self._params_fn()
            return snap["params"]

        def health_fn():
            return self.health_record(params=snap.pop("params", None))

        self._mgr.enable_emergency_save(
            params_fn=params_fn,
            step_fn=lambda: self._applied,
            extras_fn=self._extras,
            health_fn=health_fn)

    def _disarm(self):
        if self._emergency:
            self._mgr.disable_emergency_save()


def run_supervised(trainer, step_fn, data, num_steps, resume=None,
                   **kwargs):
    """Convenience: build a `TrainingSupervisor` and run it (`resume`
    forwards to `run`). Returns (report, supervisor) so callers can
    inspect incidents or resume with the same configuration."""
    sup = TrainingSupervisor(trainer, step_fn, data, **kwargs)
    return sup.run(num_steps, resume=resume), sup
