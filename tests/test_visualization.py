"""Visualization: print_summary table and plot_network DOT output
(reference: python/mxnet/visualization.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _net():
    data = sym.Variable("data")
    h = sym.Activation(sym.FullyConnected(data, num_hidden=8, name="fc1"),
                       act_type="relu", name="act1")
    h = sym.BatchNorm(h, name="bn1")
    return sym.FullyConnected(h, num_hidden=2, name="fc2")


def test_print_summary_with_shapes(capsys):
    out = mx.visualization.print_summary(_net(), shape={"data": (4, 16)})
    assert "fc1" in out and "FullyConnected" in out
    assert "(8, 16)" in out  # inferred weight shape shown


def test_plot_network_dot(tmp_path):
    g = mx.visualization.plot_network(_net(), title="mlp")
    src = g.source
    assert src.startswith('digraph "mlp"')
    assert '"fc1" -> "act1"' in src
    # weights folded away by default
    assert "fc1_weight" not in src
    g2 = mx.visualization.plot_network(_net(), hide_weights=False)
    assert "fc1_weight" in g2.source
    p = g.save(str(tmp_path / "net.dot"))
    with open(p) as f:
        assert f.read() == src
