"""RecordIO tests (SURVEY.md §1 serialization row; reference:
tests/python/unittest/test_recordio.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(bytes([i]) * (i * 7 + 1))
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert r.read() == bytes([i]) * (i * 7 + 1)
    assert r.read() is None
    r.reset()
    assert r.read() == b"\x00"
    r.close()


def test_recordio_magic_framing(tmp_path):
    """Framing matches the reference format: magic + lrec + 4-byte pad."""
    import struct
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"abcde")  # 5 bytes -> 3 pad
    w.close()
    blob = open(path, "rb").read()
    magic, lrec = struct.unpack("<II", blob[:8])
    assert magic == 0xced7230a
    assert lrec >> 29 == 0 and (lrec & ((1 << 29) - 1)) == 5
    assert blob[8:13] == b"abcde"
    assert len(blob) == 16  # 8 header + 5 data + 3 pad


def test_indexed_recordio(tmp_path):
    rec_p = str(tmp_path / "a.rec")
    idx_p = str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx_p, rec_p, "w")
    for i in range(10):
        w.write_idx(i, f"record{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_p, rec_p, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record7"
    assert r.read_idx(2) == b"record2"  # random access, out of order
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.5, 42, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.id == 42 and abs(h2.label - 3.5) < 1e-6


def test_pack_unpack_multi_label():
    h = recordio.IRHeader(3, np.array([1.0, 2.0, 3.0], np.float32), 7, 0)
    s = recordio.pack(h, b"x")
    h2, payload = recordio.unpack(s)
    assert payload == b"x"
    np.testing.assert_allclose(h2.label, [1.0, 2.0, 3.0])


def test_pack_unpack_img(tmp_path):
    img = (np.random.RandomState(0).rand(32, 32, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s, iscolor=1)
    np.testing.assert_array_equal(img, img2)  # png is lossless


def test_image_record_iter_reads_rec(tmp_path):
    """ImageRecordIter on a generated .rec yields the packed images."""
    path = str(tmp_path / "im.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    imgs = []
    for i in range(8):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        imgs.append(img)
        w.write(recordio.pack_img(recordio.IRHeader(0, float(i % 3), i, 0),
                                  img, img_fmt=".png"))
    w.close()

    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=4)
    batch = it.next()
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    assert data.shape == (4, 3, 8, 8)
    np.testing.assert_allclose(data[0], imgs[0].astype(np.float32)
                               .transpose(2, 0, 1))
    np.testing.assert_allclose(label, [0, 1, 2, 0])
    batch2 = it.next()
    with pytest.raises(StopIteration):
        it.next()


def test_image_record_iter_indexed_lazy(tmp_path):
    """With an .idx sidecar the iterator random-accesses lazily (no
    whole-file load) and reset() re-iterates."""
    rec_p = str(tmp_path / "im.rec")
    idx_p = str(tmp_path / "im.idx")
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx_p, rec_p, "w")
    for i in range(6):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec_p, data_shape=(3, 8, 8),
                               batch_size=3)
    assert it.num_samples == 6
    b1 = it.next()
    b2 = it.next()
    np.testing.assert_allclose(b2.label[0].asnumpy(), [3, 4, 5])
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    np.testing.assert_allclose(it.next().label[0].asnumpy(), [0, 1, 2])


def test_record_file_dataset(tmp_path):
    from mxnet_tpu.gluon.data import RecordFileDataset
    rec_p = str(tmp_path / "d.rec")
    idx_p = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx_p, rec_p, "w")
    for i in range(6):
        w.write_idx(i, f"item{i}".encode())
    w.close()
    ds = RecordFileDataset(rec_p)          # picks up the .idx sidecar
    assert len(ds) == 6
    assert ds[4] == b"item4"
    # and without the index (sequential load)
    import os
    os.remove(idx_p)
    ds2 = RecordFileDataset(rec_p)
    assert len(ds2) == 6 and ds2[1] == b"item1"


def test_multipart_record_framing(tmp_path):
    """Multi-part framing (cflag 1/2/3) round-trips; exercised with a
    shrunken chunk limit so the test stays small."""
    import mxnet_tpu.recordio as rio
    path = str(tmp_path / "big.rec")
    old = rio._MAX_CHUNK
    rio._MAX_CHUNK = 16
    try:
        w = rio.MXRecordIO(path, "w")
        payload = bytes(range(256)) * 2   # 512 bytes -> 32 chunks
        w.write(payload)
        w.write(b"after")
        w.close()
        r = rio.MXRecordIO(path, "r")
        assert r.read() == payload
        assert r.read() == b"after"
        r.close()
    finally:
        rio._MAX_CHUNK = old


def test_im2rec_tool(tmp_path):
    """tools/im2rec.py builds .lst/.rec/.idx that our readers consume."""
    import subprocess, sys, os
    from PIL import Image
    root = tmp_path / "imgs"
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            arr = (np.random.RandomState(hash(cls) % 100 + i)
                   .rand(10, 10, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(root / cls / f"{i}.png")
    prefix = str(tmp_path / "pack")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "im2rec.py"),
         prefix, str(root)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    # .lst has 6 entries with labels 0 (cat) and 1 (dog)
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    labels = sorted({float(l.split("\t")[1]) for l in lines})
    assert labels == [0.0, 1.0]
    # readable by MXIndexedRecordIO + unpack_img
    r = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(r.keys) == 6
    h, img = recordio.unpack_img(r.read_idx(0), iscolor=1)
    assert img.shape == (10, 10, 3)
    r.close()
    # and by ImageRecordIter
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 10, 10), batch_size=6)
    batch = it.next()
    assert batch.data[0].shape == (6, 3, 10, 10)


def _native_available():
    import mxnet_tpu.recordio as rio
    ok = rio._load_native() is not None
    if not ok and os.environ.get("MXTPU_REQUIRE_NATIVE") == "1":
        raise AssertionError("MXTPU_REQUIRE_NATIVE=1 but native recordio "
                             "library failed to build")
    return ok


def test_native_record_reader(tmp_path):
    """cpp/recordio.cc mmap reader parses Python-written files, including
    multi-part framing, and matches the Python reader byte for byte."""
    import mxnet_tpu.recordio as rio
    path = str(tmp_path / "n.rec")
    old = rio._MAX_CHUNK
    rio._MAX_CHUNK = 16
    try:
        w = rio.MXRecordIO(path, "w")
        payloads = [b"short", bytes(range(200)), b"x" * 63, b""]
        for p in payloads:
            w.write(p)
        w.close()
    finally:
        rio._MAX_CHUNK = old
    if not _native_available():
        pytest.skip("native recordio library not buildable here")
    native = rio.NativeRecordFile(path)
    assert len(native) == len(payloads)
    for i, p in enumerate(payloads):
        assert native[i] == p
    native.close()


def test_open_record_file_uses_native(tmp_path):
    import mxnet_tpu.recordio as rio
    path = str(tmp_path / "o.rec")
    w = rio.MXRecordIO(path, "w")
    for i in range(4):
        w.write(f"r{i}".encode())
    w.close()
    rf = rio.open_record_file(path)
    assert len(rf) == 4 and rf[2] == b"r2"
    if _native_available():
        assert isinstance(rf, rio.NativeRecordFile)


def test_image_record_iter_native_no_idx(tmp_path):
    """Without an .idx, the iterator gets random access + a real
    num_samples from the native reader (no whole-file python scan)."""
    path = str(tmp_path / "nn.rec")
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write(recordio.pack_img(recordio.IRHeader(0, float(i), i, 0),
                                  img, img_fmt=".png"))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                               batch_size=5)
    assert it.num_samples == 5
    batch = it.next()
    np.testing.assert_allclose(batch.label[0].asnumpy(), [0, 1, 2, 3, 4])


def test_recordio_truncated_file_never_hangs(tmp_path):
    """A truncated .rec either yields the intact prefix records or
    raises MXNetError on a torn record — the reader must terminate
    (mid-header truncation = clean EOF, mid-payload = error)."""
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(5):
        w.write(b"payload-%d" % i * 10)
    w.close()
    raw = open(path, "rb").read()
    bad = str(tmp_path / "bad.rec")
    for cut in (1, 7, len(raw) // 3, len(raw) - 3):
        open(bad, "wb").write(raw[:cut])
        try:
            r = recordio.MXRecordIO(bad, "r")
            n = 0
            while r.read() is not None:
                n += 1
            assert n <= 5
        except mx.base.MXNetError:
            pass  # torn record rejected — also fine
